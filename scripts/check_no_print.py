#!/usr/bin/env python
"""Static check: no ``print(`` in the package outside the explicit allowlist.

Telemetry must flow through the registry/logger/emit layer — stray prints
bypass the CloudWatch metric-definition contract and pollute the HPO stdout
scrape surface. The allowlist names the files whose prints ARE a stdout
contract:

* training/callbacks.py      — EvaluationMonitor HPO eval lines
* training/algorithm_train.py — CV metric lines (same HPO regex contract)
* version_contract.py        — CLI verdict for the image build
* telemetry/emit.py          — the structured-record sink itself (uses
  sys.stdout.write, listed defensively)

Detection is AST-based (calls to the ``print`` builtin), so strings and
comments mentioning print() don't trip it. Exit 0 clean, 1 with findings,
2 on unparseable files. Wired into tox (fast/full) and the tier-1 suite
(tests/test_telemetry.py).
"""

import ast
import os
import sys

PACKAGE = "sagemaker_xgboost_container_tpu"

ALLOWLIST = {
    "training/callbacks.py",
    "training/algorithm_train.py",
    "version_contract.py",
    "telemetry/emit.py",
}


def find_print_calls(source, filename):
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as e:
        raise RuntimeError("cannot parse {}: {}".format(filename, e))
    calls = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            calls.append(node.lineno)
    return calls


def check(repo_root):
    pkg_root = os.path.join(repo_root, PACKAGE)
    findings = []
    errors = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
            if rel in ALLOWLIST:
                continue
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                for lineno in find_print_calls(source, path):
                    findings.append("{}/{}:{}".format(PACKAGE, rel, lineno))
            except RuntimeError as e:
                errors.append(str(e))
    return findings, errors


def main(argv=None):
    repo_root = (argv or sys.argv[1:] or [None])[0] or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    findings, errors = check(repo_root)
    for err in errors:
        sys.stderr.write(err + "\n")
    for finding in findings:
        sys.stderr.write(
            "print() outside allowlist: {} (route output through "
            "telemetry.emit_metric or a logger)\n".format(finding)
        )
    if errors:
        return 2
    if findings:
        return 1
    sys.stderr.write("check_no_print: OK\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
