#!/usr/bin/env python
"""DEPRECATED shim: the no-print policy now lives in graftlint.

This script shipped in PR 1 as a standalone AST gate; the policy (and the
allowlist) moved to the ``no-print`` rule of the repo's static analyzer
(``sagemaker_xgboost_container_tpu/toolkit/graftlint``, see
docs/static-analysis.md). The shim keeps the historical entrypoint and
module API (``find_print_calls``, ``ALLOWLIST``) working for existing
tox/ci.sh invocations and tests; new wiring should invoke the analyzer
directly::

    python scripts/graftlint.py --select no-print

(graftlint is loaded through ``scripts/graftlint.py`` rather than as a
product submodule so the gate still reports — exit 2 — on a tree whose
package ``__init__`` chain doesn't even import.)

Exit codes unchanged: 0 clean, 1 with findings, 2 on unparseable files.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

from graftlint import load_submodule  # noqa: E402  (scripts/graftlint.py)

_legacy = load_submodule("passes.legacy")
ALLOWLIST = _legacy.PRINT_ALLOWLIST
find_print_calls = _legacy.find_print_calls

__all__ = ["ALLOWLIST", "find_print_calls", "main"]


def main(argv=None):
    graftlint_main = load_submodule("__main__").main

    repo_root = (argv or sys.argv[1:] or [None])[0] or REPO_ROOT
    sys.stderr.write(
        "check_no_print: deprecated shim over graftlint's no-print rule "
        "(docs/static-analysis.md)\n"
    )
    return graftlint_main(["--root", repo_root, "--select", "no-print"])


if __name__ == "__main__":
    sys.exit(main())
