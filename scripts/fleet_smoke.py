#!/usr/bin/env python
"""Fleet-observability smoke: a tiny 2-rank loopback run producing one
merged ``trace-fleet.json`` (pid=rank lanes) plus a live ``/status`` check.

Sibling of ``trace_smoke.py``: rank 0 is a real traced training run whose
spans ship over the loopback fleet plane; rank 1 is a synthetic shipper
feeding fabricated round spans through the same framed-TCP path, so the
collector exercises the full merge + per-round skew fold without a second
process. ``scripts/ci.sh`` archives the merged trace under
``${CI_ARTIFACT_DIR:-.ci-artifacts}/traces/`` next to the per-rank export.

Exit codes: 0 OK, 1 the merged trace / skew fold / status endpoint failed.
"""

import json
import os
import socket
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SM_TRACE"] = "1"
os.environ["SM_FLEET_TRACE"] = "1"
os.environ["SM_FLEET_FLUSH_S"] = "0.2"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _free_port():
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def _fail(msg):
    sys.stderr.write("fleet smoke FAILED: {}\n".format(msg))
    return 1


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_dir = argv[0] if argv else os.path.join(".ci-artifacts", "traces")
    os.environ["SM_TRACE_EXPORT_DIR"] = out_dir
    os.environ["SM_FLEET_TRACE_PORT"] = str(_free_port())
    os.environ["SM_STATUS_PORT"] = str(_free_port())

    import urllib.request

    import numpy as np

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train
    from sagemaker_xgboost_container_tpu.telemetry import fleet, tracing
    from sagemaker_xgboost_container_tpu.training.profiling import RoundTimer

    hosts = ["algo-1", "algo-2"]
    tracing.set_rank(0)
    plane = fleet.start_fleet_plane(hosts, "algo-1")
    if plane is None or plane.collector is None:
        return _fail("fleet plane did not start a rank-0 collector")
    try:
        rng = np.random.RandomState(0)
        X = rng.rand(256, 4).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.float32)
        rounds = 3
        train(
            {"objective": "binary:logistic", "max_depth": 3},
            DataMatrix(X, labels=y),
            num_boost_round=rounds,
            callbacks=[RoundTimer(num_rows=256, log_every=0, emit_structured=False)],
        )

        # rank 1: fabricated fast-lane spans for the same round ids, shipped
        # through the real framed-TCP path so the collector folds a full
        # 2-rank skew report per round
        def rank1_spans():
            wire = []
            for r in range(rounds):
                base = float(r) * 10_000.0
                wire.append(
                    {
                        "name": "host_dispatch",
                        "trace_id": "smoke-r1-{}".format(r),
                        "span_id": "smoke-r1-h{}".format(r),
                        "start_us": base + 10.0,
                        "dur_us": 200.0,
                        "tid": 1,
                        "thread_name": "MainThread",
                    }
                )
                wire.append(
                    {
                        "name": "round",
                        "trace_id": "smoke-r1-{}".format(r),
                        "span_id": "smoke-r1-{}".format(r),
                        "start_us": base,
                        "dur_us": 500.0,
                        "tid": 1,
                        "thread_name": "MainThread",
                        "attributes": {"round": r},
                    }
                )
            return wire

        shipper = fleet.SpanShipper(
            rank=1,
            host="algo-2",
            collector_addr=("127.0.0.1", plane.collector.port),
            interval=0.2,
            span_source=rank1_spans,
        )
        if not shipper.send_once():
            return _fail("rank-1 synthetic span batch did not deliver")

        # /status while the plane is live
        status_port = int(os.environ["SM_STATUS_PORT"])
        with urllib.request.urlopen(
            "http://127.0.0.1:{}/status".format(status_port), timeout=5
        ) as resp:
            status = json.loads(resp.read().decode("utf-8"))
        if "round" not in status or "uptime_s" not in status:
            return _fail("/status payload missing round/uptime_s: {}".format(status))

        path = fleet.export_fleet_trace(default_dir=out_dir)
        if not path or not os.path.isfile(path):
            return _fail("no merged trace-fleet.json produced")
        with open(path) as f:
            doc = json.load(f)
        spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
        lanes = {e["pid"] for e in spans}
        if lanes != {0, 1}:
            return _fail("expected pid lanes {{0, 1}}, got {}".format(sorted(lanes)))
        round_ids = {}
        for e in spans:
            if e["name"] == "round" and "round" in e.get("args", {}):
                round_ids.setdefault(e["pid"], set()).add(e["args"]["round"])
        shared = round_ids.get(0, set()) & round_ids.get(1, set())
        if len(shared) < rounds:
            return _fail(
                "rank lanes do not share round ids: {}".format(round_ids)
            )

        # the skew fold saw both ranks for every round
        deadline = time.time() + 5.0
        reports = plane.collector.skew_snapshot()
        while len(reports) < rounds and time.time() < deadline:
            time.sleep(0.05)
            reports = plane.collector.skew_snapshot()
        if len(reports) < rounds:
            return _fail("expected {} skew reports, got {}".format(rounds, reports))
    finally:
        fleet.stop_fleet_plane()

    print(
        "fleet smoke OK: {} ({} spans, lanes {}, {} skew reports)".format(
            path, len(spans), sorted(lanes), len(reports)
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
