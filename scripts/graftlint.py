#!/usr/bin/env python
"""Standalone graftlint entrypoint — the robust CI invocation.

``python -m sagemaker_xgboost_container_tpu.toolkit.graftlint`` imports the
product package's ancestor ``__init__`` chain on the way in (which pulls in
jax and the algorithm modules), so on a tree whose package modules don't
even parse — the very situation a lint gate exists to report (exit 2) — the
CLI would die with a raw import traceback before argparse runs. The
analyzer itself is dependency-free and never imports the code it checks;
this launcher extends that property to the *entrypoint* by loading the
graftlint subpackage under a private alias via importlib, executing no
ancestor ``__init__`` and no product code.

Same CLI, same exit codes: 0 clean, 1 findings, 2 broken tree / tool error.
"""

import importlib
import importlib.util
import os
import sys

#: private top-level alias: graftlint only uses intra-package relative
#: imports, so it runs identically under any package name
_ALIAS = "_graftlint_standalone"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GRAFTLINT_DIR = os.path.join(
    REPO_ROOT, "sagemaker_xgboost_container_tpu", "toolkit", "graftlint"
)


def load_graftlint():
    """The graftlint package, imported without touching the product package."""
    if _ALIAS in sys.modules:
        return sys.modules[_ALIAS]
    spec = importlib.util.spec_from_file_location(
        _ALIAS,
        os.path.join(GRAFTLINT_DIR, "__init__.py"),
        submodule_search_locations=[GRAFTLINT_DIR],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[_ALIAS] = mod
    spec.loader.exec_module(mod)
    return mod


def load_submodule(dotted):
    """A graftlint submodule (e.g. ``passes.legacy``) via the alias."""
    load_graftlint()
    return importlib.import_module(_ALIAS + "." + dotted)


def main(argv=None):
    return load_submodule("__main__").main(argv)


if __name__ == "__main__":
    sys.exit(main())
