#!/usr/bin/env python
"""Fold per-PR bench snapshots into one performance-trajectory report.

The driver leaves ``BENCH_r*.json`` (single-host bench.py runs) and
``MULTICHIP_r*.json`` (multi-device smoke results) at the repo root, one per
PR round. Each snapshot is a point; nobody looks at the line. This tool folds
them into a single trajectory document — rounds/sec, vs_baseline, and whether
the backend probe failed, per snapshot — so a regression shows up as a bend
in the curve rather than a forgotten file.

Usage:
    python scripts/bench_trend.py                 # report on stdout
    python scripts/bench_trend.py --out trend.json
    python scripts/bench_trend.py --gate 0.15     # exit 1 if the newest
                                                  # snapshot regressed >15%
                                                  # below the best prior one

Gate semantics: only snapshots from the same measurement family (same
backend-fallback status) are compared, so a CPU-fallback point is never
gated against a real accelerator point. Exit codes: 0 ok, 1 regression
beyond tolerance, 2 tool error (unreadable snapshot, no data).

``ci.sh full`` runs this and archives the report under
``$CI_ARTIFACT_DIR/bench/``.
"""

import argparse
import glob
import json
import os
import re
import sys


def _snapshot_n(path, doc):
    """Round index: the 'n' key, else the r<NN> filename suffix, else -1."""
    n = doc.get("n")
    if isinstance(n, int):
        return n
    m = re.search(r"r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def _load(path):
    with open(path) as f:
        return json.load(f)


def _bench_point(path, doc):
    parsed = doc.get("parsed") or {}
    metric = parsed.get("metric", "")
    point = {
        "n": _snapshot_n(path, doc),
        "file": os.path.basename(path),
        "rc": doc.get("rc"),
        "value": parsed.get("value"),
        "unit": parsed.get("unit"),
        "vs_baseline": parsed.get("vs_baseline"),
        "cpu_fallback": "[CPU FALLBACK" in metric,
        "backend_init_error": bool(parsed.get("backend_init_error")),
    }
    # newer bench.py lines carry richer shape — surface it when present
    for key in ("p50_ms", "p95_ms", "rounds_per_dispatch"):
        if key in parsed:
            point[key] = parsed[key]
    roofline = parsed.get("roofline")
    if isinstance(roofline, dict):
        point["roofline_binding"] = roofline.get("binding")
    # model-quality stamp (SM_MODEL_TELEMETRY): a perf win that degrades
    # the train metric shows as a bend in THIS curve too
    model = parsed.get("model")
    if isinstance(model, dict):
        if model.get("train_metric") is not None:
            point["train_metric"] = model["train_metric"]
            point["train_value"] = model.get("train_value")
        learning = model.get("learning")
        if isinstance(learning, dict) and "grad_nonfinite" in learning:
            point["grad_nonfinite"] = learning["grad_nonfinite"]
    return point


def _multichip_point(path, doc):
    return {
        "n": _snapshot_n(path, doc),
        "file": os.path.basename(path),
        "n_devices": doc.get("n_devices"),
        "rc": doc.get("rc"),
        "ok": doc.get("ok"),
        "skipped": doc.get("skipped"),
    }


def build_report(snapshot_dir):
    """Fold every BENCH_*/MULTICHIP_* snapshot in ``snapshot_dir`` into one
    trajectory doc (points sorted by round index)."""
    bench, multichip, errors = [], [], []
    for path in sorted(glob.glob(os.path.join(snapshot_dir, "BENCH_*.json"))):
        try:
            bench.append(_bench_point(path, _load(path)))
        except (OSError, ValueError) as e:
            errors.append({"file": os.path.basename(path), "error": str(e)})
    for path in sorted(glob.glob(os.path.join(snapshot_dir, "MULTICHIP_*.json"))):
        try:
            multichip.append(_multichip_point(path, _load(path)))
        except (OSError, ValueError) as e:
            errors.append({"file": os.path.basename(path), "error": str(e)})
    bench.sort(key=lambda p: p["n"])
    multichip.sort(key=lambda p: p["n"])

    values = [p["value"] for p in bench if isinstance(p["value"], (int, float))]
    summary = {}
    if values:
        latest = bench[-1]
        summary = {
            "snapshots": len(bench),
            "latest_n": latest["n"],
            "latest_value": latest["value"],
            "latest_vs_baseline": latest["vs_baseline"],
            "best_value": max(values),
            "worst_value": min(values),
            "any_backend_init_error": any(p["backend_init_error"] for p in bench),
            "all_cpu_fallback": all(p["cpu_fallback"] for p in bench),
        }
    return {
        "report": "bench_trend",
        "dir": os.path.abspath(snapshot_dir),
        "bench": bench,
        "multichip": multichip,
        "summary": summary,
        "errors": errors,
    }


def gate(report, tolerance):
    """Regression check: newest bench value vs the best PRIOR value in the
    same family (same cpu_fallback flag). Returns (ok, message)."""
    bench = report["bench"]
    usable = [p for p in bench if isinstance(p.get("value"), (int, float))]
    if len(usable) < 2:
        return True, "gate skipped: fewer than 2 comparable snapshots"
    newest = usable[-1]
    prior = [
        p for p in usable[:-1] if p["cpu_fallback"] == newest["cpu_fallback"]
    ]
    if not prior:
        return True, (
            "gate skipped: no prior snapshot in the same backend family "
            "(newest cpu_fallback={})".format(newest["cpu_fallback"])
        )
    best_prior = max(p["value"] for p in prior)
    floor = best_prior * (1.0 - tolerance)
    if newest["value"] < floor:
        return False, (
            "REGRESSION: snapshot n={} at {:.3f} {} is {:.1f}% below the "
            "best prior ({:.3f}), tolerance {:.0f}%".format(
                newest["n"], newest["value"], newest.get("unit") or "",
                (1.0 - newest["value"] / best_prior) * 100.0,
                best_prior, tolerance * 100.0,
            )
        )
    return True, (
        "ok: snapshot n={} at {:.3f} within {:.0f}% of best prior {:.3f}".format(
            newest["n"], newest["value"], tolerance * 100.0, best_prior
        )
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--dir",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_*.json / MULTICHIP_*.json (default: repo root)",
    )
    ap.add_argument("--out", default=None, help="write the report to this file")
    ap.add_argument(
        "--gate",
        type=float,
        default=None,
        metavar="TOL",
        help="fail (exit 1) if the newest snapshot is more than TOL "
        "(fraction, e.g. 0.15) below the best prior same-family value",
    )
    args = ap.parse_args(argv)

    report = build_report(args.dir)
    if not report["bench"] and not report["multichip"]:
        sys.stderr.write("bench_trend: no snapshots found in {}\n".format(args.dir))
        return 2
    if report["errors"]:
        for err in report["errors"]:
            sys.stderr.write(
                "bench_trend: unreadable snapshot {file}: {error}\n".format(**err)
            )

    rc = 0
    if args.gate is not None:
        ok, message = gate(report, args.gate)
        report["gate"] = {"tolerance": args.gate, "ok": ok, "message": message}
        sys.stderr.write("bench_trend gate: {}\n".format(message))
        if not ok:
            rc = 1

    text = json.dumps(report, indent=2, sort_keys=True)
    if args.out:
        out_dir = os.path.dirname(os.path.abspath(args.out))
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text + "\n")
        sys.stderr.write("bench_trend: report written to {}\n".format(args.out))
    else:
        sys.stdout.write(text + "\n")
    return rc if not report["errors"] else (rc or 2)


if __name__ == "__main__":
    sys.exit(main())
