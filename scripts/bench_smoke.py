#!/usr/bin/env python
"""Fused-dispatch smoke: K=1 vs K=4 on a tiny synthetic task, bounded.

Two claims of the fused round pipeline (docs/DESIGN.md §Round pipeline) are
cheap to verify on every CI run and expensive to discover broken later:

* **bit-identity** — committed trees and predictions under
  ``_rounds_per_dispatch=4`` are u32-view identical to the K=1 synchronous
  path (the contract every perf change must keep);
* **not slower** — fusing K rounds into one ``lax.scan`` dispatch amortizes
  the per-round Python + dispatch overhead, so the fused per-round wall time
  must not exceed the K=1 time by more than ``BENCH_SMOKE_TOL`` (default
  1.35 — a guardrail against the scan path regressing into re-compiles or
  extra transfers, not a microbenchmark).

A third bounded cell covers the 2-D communication-optimal lowering: on a
forced-host-platform virtual device mesh (data x feature), committed trees
and predictions under ``GRAFT_HIST_COMM=reduce_scatter`` must be u32-view
identical to psum — the two-axis winner merge the 2-D scale path depends
on. Skipped (and recorded as skipped) below 2 devices.

Sized to stay well under 60 s on the CI CPU (tiny rows, shallow trees,
single measurement window after a compile warmup). The measured numbers are
archived as JSON under the argv[1] directory (``ci.sh`` passes
``${CI_ARTIFACT_DIR:-.ci-artifacts}/bench``).

Exit codes: 0 OK, 1 bit-identity or speed assertion failed.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the 2-D comm cell needs a virtual device mesh; force the host-platform
# device count BEFORE jax imports (no-op when the caller already forces one)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

N_ROWS = int(os.environ.get("BENCH_SMOKE_ROWS", "20000"))
N_FEATURES = 8
MAX_DEPTH = 4
MEASURE_ROUNDS = 12
TOL = float(os.environ.get("BENCH_SMOKE_TOL", "1.35"))


def _session(dtrain, k):
    from sagemaker_xgboost_container_tpu.models.booster import (
        TrainConfig,
        _TrainingSession,
    )
    from sagemaker_xgboost_container_tpu.models.forest import Forest

    cfg = TrainConfig(
        {
            "objective": "binary:logistic",
            "max_depth": MAX_DEPTH,
            "max_bin": 64,
            "_rounds_per_dispatch": k,
        }
    )
    forest = Forest(
        objective_name=cfg.objective,
        base_score=cfg.base_score,
        num_feature=dtrain.num_col,
    )
    return _TrainingSession(cfg, dtrain, [], forest)


def _rate(session):
    """Measured per-round wall seconds after a compile warmup dispatch."""
    import jax

    session.run_rounds()  # compile + warm
    jax.block_until_ready(session.margins)
    done = 0
    t0 = time.perf_counter()
    while done < MEASURE_ROUNDS:
        done += len(session.run_rounds()[0])
        jax.block_until_ready(session.margins)
    return (time.perf_counter() - t0) / done


def _mesh2d_comm_cell(dtrain, X):
    """psum vs reduce_scatter on a (data x feature) mesh of the forced
    virtual devices -> result dict (``skipped`` below 2 devices)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from sagemaker_xgboost_container_tpu.models import train

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"skipped": "need >= 2 devices, found {}".format(n_dev)}
    shape = (n_dev // 2, 2) if n_dev >= 4 else (1, 2)
    mesh = Mesh(
        np.array(jax.devices()[: shape[0] * shape[1]]).reshape(shape),
        axis_names=("data", "feature"),
    )
    params = {"objective": "binary:logistic", "max_depth": 3, "max_bin": 64,
              "seed": 11}
    preds = {}
    prev = os.environ.get("GRAFT_HIST_COMM")
    try:
        for comm in ("psum", "reduce_scatter"):
            os.environ["GRAFT_HIST_COMM"] = comm
            f = train(dict(params), dtrain, num_boost_round=2, mesh=mesh)
            preds[comm] = np.asarray(f.predict(X), np.float32)
    finally:
        if prev is None:
            os.environ.pop("GRAFT_HIST_COMM", None)
        else:
            os.environ["GRAFT_HIST_COMM"] = prev
    return {
        "shape": "{}x{}".format(*shape),
        "bitwise_identical": bool(
            np.array_equal(
                preds["psum"].view(np.uint32),
                preds["reduce_scatter"].view(np.uint32),
            )
        ),
    }


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_dir = argv[0] if argv else os.path.join(".ci-artifacts", "bench")

    import numpy as np

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train

    rng = np.random.RandomState(0)
    X = rng.randn(N_ROWS, N_FEATURES).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
    dtrain = DataMatrix(X, labels=y)

    # --- bit-identity: K=1 vs K=4 committed forests -----------------------
    params = {"objective": "binary:logistic", "max_depth": MAX_DEPTH,
              "max_bin": 64, "seed": 7}
    f1 = train(dict(params), dtrain, num_boost_round=4)
    f4 = train(dict(params, _rounds_per_dispatch=4), dtrain, num_boost_round=4)
    p1 = np.asarray(f1.predict(X), np.float32)
    p4 = np.asarray(f4.predict(X), np.float32)
    bitwise = bool(np.array_equal(p1.view(np.uint32), p4.view(np.uint32)))

    # --- throughput: fused dispatch must not be slower --------------------
    s_k1 = _rate(_session(dtrain, 1))
    s_k4 = _rate(_session(dtrain, 4))

    # --- 2-D mesh comm cell: reduce_scatter x feature axis bit-identity ---
    mesh2d = _mesh2d_comm_cell(dtrain, X)

    doc = {
        "rows": N_ROWS,
        "measure_rounds": MEASURE_ROUNDS,
        "k1_round_s": round(s_k1, 6),
        "k4_round_s": round(s_k4, 6),
        "k4_speedup": round(s_k1 / max(s_k4, 1e-9), 3),
        "tolerance": TOL,
        "bitwise_identical": bitwise,
        "mesh2d_comm": mesh2d,
    }
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "bench_smoke.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    sys.stderr.write("bench smoke: {}\n".format(json.dumps(doc)))

    if not bitwise:
        sys.stderr.write(
            "bench smoke FAILED: K=4 trees/predictions diverge bitwise "
            "from K=1\n"
        )
        return 1
    if s_k4 > s_k1 * TOL:
        sys.stderr.write(
            "bench smoke FAILED: fused K=4 dispatch is slower than K=1 "
            "({:.4f}s vs {:.4f}s per round, tol {}x)\n".format(s_k4, s_k1, TOL)
        )
        return 1
    if not mesh2d.get("skipped") and not mesh2d.get("bitwise_identical"):
        sys.stderr.write(
            "bench smoke FAILED: 2-D mesh reduce_scatter predictions "
            "diverge bitwise from psum ({})\n".format(mesh2d)
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
