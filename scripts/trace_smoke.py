#!/usr/bin/env python
"""Trace-export smoke: train a tiny model with tracing armed and export the
Chrome trace, validating the artifact before CI archives it.

``scripts/ci.sh`` runs this after the test tiers and archives the exported
JSON under ``${CI_ARTIFACT_DIR:-.ci-artifacts}/traces/`` next to
``graftlint.json`` — every CI run leaves a real, loadable timeline behind
(chrome://tracing / Perfetto), so "what does a round look like right now"
is answerable from artifacts alone.

Exit codes: 0 OK, 1 the export is missing/empty/not a span tree.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SM_TRACE"] = "1"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_dir = argv[0] if argv else os.path.join(".ci-artifacts", "traces")
    os.environ["SM_TRACE_EXPORT_DIR"] = out_dir
    # sample every dispatch so the artifact carries the host/device split
    os.environ.setdefault("SM_TRACE_DEVICE_SYNC", "1")

    import numpy as np

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train
    from sagemaker_xgboost_container_tpu.telemetry import (
        register_runtime_gauges,
        tracing,
    )
    from sagemaker_xgboost_container_tpu.training.profiling import RoundTimer

    register_runtime_gauges()
    rng = np.random.RandomState(0)
    X = rng.rand(256, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    train(
        {"objective": "binary:logistic", "max_depth": 3},
        DataMatrix(X, labels=y),
        num_boost_round=3,
        callbacks=[RoundTimer(num_rows=256, log_every=0, emit_structured=False)],
    )
    path = tracing.export_traces(default_dir=out_dir)
    if not path or not os.path.isfile(path):
        sys.stderr.write("trace smoke FAILED: no export file produced\n")
        return 1
    with open(path) as f:
        doc = json.load(f)
    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    rounds = [e for e in spans if e["name"] == "round"]
    if not rounds:
        sys.stderr.write(
            "trace smoke FAILED: {} has no round spans ({} events)\n".format(
                path, len(spans)
            )
        )
        return 1
    print(
        "trace smoke OK: {} ({} spans, {} rounds)".format(
            path, len(spans), len(rounds)
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
