#!/usr/bin/env python
"""Serving lifecycle chaos drill: SIGTERM mid-flight + wedged-predict paths.

Self-spawning harness (parent mode spawns a real server child running this
same file) exercising the serving lifecycle plane end to end over real HTTP:

* ``--mode drain`` — graceful drain: the child serves a trained model with
  every batcher dispatch slowed (``batcher.dispatch:sleep``); the parent
  launches concurrent clients and SIGTERMs the server while their requests
  are in flight. Asserts: **zero dropped in-flight responses** (every
  accepted request completes 200 with a full, parseable body), new connects
  during the drain get **503 + Retry-After** (both ``/invocations`` and
  ``/ping``), the stdout lifecycle records walk ``draining → stopped``, and
  the child exits **0**.
* ``--mode stuck`` — wedged-predict watchdog (shed action): the 2nd
  dispatch wedges (``batcher.dispatch:sleep:300@2``); the watchdog
  (``SM_PREDICT_STUCK_S``) trips the breaker open (``/ping`` 503, new
  requests shed with Retry-After), emits one ``serving.stuck`` record, and
  leaves a flight-recorder dump. A SIGTERM then cannot drain the wedged
  request, so the child exits **83** (``EXIT_DRAIN_TIMEOUT``) with a
  ``serving.abort`` record — never a silent hang.
* ``--mode abort`` — the same wedge with ``SM_PREDICT_STUCK_ACTION=abort``:
  the watchdog itself aborts the process with **84**
  (``EXIT_PREDICT_STUCK``) so the platform restarts a clean device runtime.

Artifacts (child stdout, flight-recorder dumps) are archived under the
given directory — CI wires this into the chaos tier with
``${CI_ARTIFACT_DIR:-.ci-artifacts}/serve/``.

Exit code: 0 when every assertion holds, 1 otherwise (2 on usage errors).
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_CLIENTS = 6
ROWS = 8
FEATURES = 8


def _free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------------------------------------------- server child
def child_main(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from sagemaker_xgboost_container_tpu.serving.server import serving_entrypoint

    serving_entrypoint(port=args.port)
    return 0


# ------------------------------------------------------------------- clients
def _post(base, body, timeout=30):
    req = urllib.request.Request(
        base + "/invocations",
        data=body,
        method="POST",
        headers={"Content-Type": "text/csv"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _get(base, path, timeout=10):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _csv_payload(rows=ROWS):
    return (
        "\n".join(",".join("0.5" for _ in range(FEATURES)) for _ in range(rows))
    ).encode()


def _wait_ready(base, deadline_s=120):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        try:
            status, _, _ = _get(base, "/ping", timeout=5)
            if status == 200:
                return True
        except Exception:
            pass
        time.sleep(0.25)
    return False


def _valid_body(body, rows=ROWS):
    lines = [l for l in body.decode("utf-8").strip().splitlines() if l]
    if len(lines) != rows:
        return False
    try:
        for line in lines:
            for cell in line.split(","):
                float(cell)
    except ValueError:
        return False
    return True


# -------------------------------------------------------------------- parent
def _train_model(model_dir):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models import train

    rng = np.random.RandomState(0)
    X = rng.rand(512, FEATURES).astype(np.float32)
    y = (X @ rng.rand(FEATURES).astype(np.float32)).astype(np.float32)
    forest = train(
        {"max_depth": 3, "objective": "reg:squarederror"},
        DataMatrix(X, labels=y),
        num_boost_round=8,
    )
    os.makedirs(model_dir, exist_ok=True)
    forest.save_model(os.path.join(model_dir, "xgboost-model"))


def _spawn(mode, workdir, model_dir, port):
    env = dict(os.environ)
    for stale in ("SM_FAULT_SPEC", "SM_TRACE", "SM_PREDICT_STUCK_S",
                  "SM_PREDICT_STUCK_ACTION", "SM_REQUEST_DEADLINE_S",
                  "SM_DRAIN_TIMEOUT_S", "SM_GRACEFUL_DRAIN"):
        env.pop(stale, None)
    trace_dir = os.path.join(workdir, "trace")
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "PYTHONUNBUFFERED": "1",
            "SM_MODEL_DIR": model_dir,
            # every request takes the coalescing queue (and therefore the
            # faultable worker dispatch) — the host fast path would dodge
            # the chaos hooks
            "GRAFT_HOST_PREDICT_ROWS": "0",
            # warmup compiles would blur drill timing on a cold CPU backend
            "GRAFT_PREDICT_WARMUP": "0",
        }
    )
    if mode == "drain":
        # slow every dispatch enough that SIGTERM lands mid-flight but a
        # few batches still settle well inside the drain deadline
        env["SM_FAULT_SPEC"] = "batcher.dispatch:sleep:1.5"
        env["SM_DRAIN_TIMEOUT_S"] = "60"
    else:
        # first dispatch clean (proves the path), second wedges far past
        # every deadline in play
        env["SM_FAULT_SPEC"] = "batcher.dispatch:sleep:300@2"
        env["SM_PREDICT_STUCK_S"] = "1"
        env["SM_TRACE"] = "1"
        env["SM_TRACE_EXPORT_DIR"] = trace_dir
        env["SM_DRAIN_TIMEOUT_S"] = "3"
        if mode == "abort":
            env["SM_PREDICT_STUCK_ACTION"] = "abort"
    out = open(os.path.join(workdir, "server.out"), "w")
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.abspath(__file__),
            "--child",
            "--port", str(port),
        ],
        env=env,
        stdout=out,
        stderr=subprocess.STDOUT,
    )
    return proc, out


def _read(path):
    with open(path) as f:
        return f.read()


def _records(text, metric):
    prefix = '{{"metric": "{}"'.format(metric)
    return [json.loads(l) for l in text.splitlines() if l.startswith(prefix)]


def _check(ok, message, failures):
    print(("ok: " if ok else "FAIL: ") + message, flush=True)
    if not ok:
        failures.append(message)
    return ok


def _wait_exit(proc, out, timeout=120):
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    out.close()
    return proc.returncode


def _run_drain(workdir, model_dir, failures):
    port = _free_port()
    base = "http://127.0.0.1:{}".format(port)
    proc, out = _spawn("drain", workdir, model_dir, port)
    try:
        if not _check(_wait_ready(base), "server became ready", failures):
            return
        payload = _csv_payload()
        results = []

        def client():
            try:
                results.append(_post(base, payload, timeout=90))
            except Exception as e:  # dropped mid-flight = the bug we drill
                results.append(("EXC", repr(e), {}))

        threads = [
            threading.Thread(target=client, daemon=True) for _ in range(N_CLIENTS)
        ]
        for t in threads:
            t.start()
        time.sleep(0.7)  # first dispatch mid-sleep, the rest queued
        proc.send_signal(signal.SIGTERM)
        time.sleep(0.3)  # let begin_drain land

        # new work during the drain: orderly 503 + Retry-After, never a RST
        status, _, headers = _post(base, payload, timeout=10)
        _check(
            status == 503 and headers.get("Retry-After"),
            "new /invocations during drain got 503 + Retry-After "
            "(got {} {})".format(status, headers.get("Retry-After")),
            failures,
        )
        ping_status, _, ping_headers = _get(base, "/ping")
        _check(
            ping_status == 503 and ping_headers.get("Retry-After"),
            "/ping during drain got 503 + Retry-After (got {})".format(ping_status),
            failures,
        )

        for t in threads:
            t.join(timeout=120)
        ok = [r for r in results if r[0] == 200 and _valid_body(r[1])]
        _check(
            len(results) == N_CLIENTS and len(ok) == N_CLIENTS,
            "all {} in-flight requests completed with valid bodies "
            "({} ok, results: {})".format(
                N_CLIENTS, len(ok), [r[0] for r in results]
            ),
            failures,
        )
        rc = _wait_exit(proc, out)
        _check(rc == 0, "server drained and exited 0 (rc={})".format(rc), failures)
        text = _read(os.path.join(workdir, "server.out"))
        states = [r["state"] for r in _records(text, "serving.lifecycle")]
        _check(
            "draining" in states and "stopped" in states,
            "lifecycle records walk draining -> stopped ({})".format(states),
            failures,
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        if not out.closed:
            out.close()


def _run_stuck(workdir, model_dir, failures, abort=False):
    port = _free_port()
    base = "http://127.0.0.1:{}".format(port)
    proc, out = _spawn("abort" if abort else "stuck", workdir, model_dir, port)
    try:
        if not _check(_wait_ready(base), "server became ready", failures):
            return
        payload = _csv_payload()
        status, body, _ = _post(base, payload)
        _check(
            status == 200 and _valid_body(body),
            "first request (clean dispatch) returned 200 (got {})".format(status),
            failures,
        )

        # the wedge: its client gives up quickly; the dispatch stays stuck
        def wedged():
            try:
                _post(base, payload, timeout=4)
            except Exception:
                pass

        threading.Thread(target=wedged, daemon=True).start()

        if abort:
            rc = _wait_exit(proc, out, timeout=60)
            _check(
                rc == 84,
                "watchdog abort action exited EXIT_PREDICT_STUCK "
                "(rc={}, want 84)".format(rc),
                failures,
            )
        else:
            # shed action: breaker open -> /ping 503 + new requests shed
            deadline = time.monotonic() + 30
            ping_status = None
            while time.monotonic() < deadline:
                ping_status, _, _ = _get(base, "/ping")
                if ping_status == 503:
                    break
                time.sleep(0.25)
            _check(
                ping_status == 503,
                "watchdog tripped the breaker: /ping 503 while stuck "
                "(got {})".format(ping_status),
                failures,
            )
            status, _, headers = _post(base, payload, timeout=10)
            _check(
                status == 503 and headers.get("Retry-After"),
                "stuck endpoint sheds with 503 + Retry-After (got {})".format(status),
                failures,
            )
            # SIGTERM now: the wedged request can never drain -> exit 83
            proc.send_signal(signal.SIGTERM)
            rc = _wait_exit(proc, out, timeout=60)
            _check(
                rc == 83,
                "drain with a wedged request exited EXIT_DRAIN_TIMEOUT "
                "(rc={}, want 83)".format(rc),
                failures,
            )

        text = _read(os.path.join(workdir, "server.out"))
        stuck = _records(text, "serving.stuck")
        _check(
            len(stuck) == 1 and stuck[0].get("stuck_s", 0) >= 1,
            "exactly one serving.stuck record emitted ({})".format(len(stuck)),
            failures,
        )
        dump = stuck[0].get("flight_recorder") if stuck else None
        _check(
            bool(dump) and os.path.exists(dump),
            "serving.stuck carries a flight-recorder dump ({})".format(dump),
            failures,
        )
        aborts = _records(text, "serving.abort")
        want_reason = "predict_stuck" if abort else "drain_timeout"
        want_code = 84 if abort else 83
        _check(
            aborts
            and aborts[0]["reason"] == want_reason
            and aborts[0]["exit_code"] == want_code,
            "serving.abort names {}/{} ({})".format(
                want_reason, want_code,
                [(a.get("reason"), a.get("exit_code")) for a in aborts],
            ),
            failures,
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        if not out.closed:
            out.close()


def _archive(workdir, artifact_dir, mode):
    dest = os.path.join(artifact_dir, mode)
    os.makedirs(dest, exist_ok=True)
    src = os.path.join(workdir, "server.out")
    if os.path.exists(src):
        shutil.copy2(src, dest)
    trace_dir = os.path.join(workdir, "trace")
    if os.path.isdir(trace_dir):
        for f in os.listdir(trace_dir):
            shutil.copy2(os.path.join(trace_dir, f), os.path.join(dest, f))
    print("artifacts archived under {}".format(dest), flush=True)


def parent_main(args):
    failures = []
    modes = [args.mode] if args.mode != "all" else ["drain", "stuck", "abort"]
    artifact_dir = os.path.abspath(args.artifact_dir)
    os.makedirs(artifact_dir, exist_ok=True)
    model_dir = tempfile.mkdtemp(prefix="serve-drill-model-")
    try:
        _train_model(model_dir)
        for mode in modes:
            print("--- serve drill: {} ---".format(mode), flush=True)
            workdir = tempfile.mkdtemp(prefix="serve-drill-{}-".format(mode))
            try:
                if mode == "drain":
                    _run_drain(workdir, model_dir, failures)
                else:
                    _run_stuck(workdir, model_dir, failures, abort=(mode == "abort"))
                _archive(workdir, artifact_dir, mode)
            finally:
                shutil.rmtree(workdir, ignore_errors=True)
    finally:
        shutil.rmtree(model_dir, ignore_errors=True)
    if failures:
        print("SERVE DRILL FAILED ({} assertion(s))".format(len(failures)), flush=True)
        return 1
    print("SERVE DRILL OK", flush=True)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("artifact_dir", nargs="?", default=".ci-artifacts/serve")
    parser.add_argument(
        "--mode", choices=["drain", "stuck", "abort", "all"], default="all"
    )
    parser.add_argument("--child", action="store_true")
    parser.add_argument("--port", type=int)
    args = parser.parse_args(argv)
    if args.child:
        return child_main(args)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
