"""Stdlib line-coverage gate (PEP 669) — the reference's --cov-fail-under=60
(tox.ini:29-30) made real in an environment where pytest-cov cannot be
installed.

A pytest plugin built on ``sys.monitoring`` (py3.12+): registers LINE events
for code objects whose filename sits under the measured package, records the
set of executed lines per file, and compares against the set of executable
lines (derived from each code object's ``co_lines()``, the same source of
truth the interpreter uses — so docstrings/blank lines/comments are excluded
exactly like coverage.py's arc-less line mode).

Usage:
    python -m pytest tests/ -q -p scripts.covgate [--covgate-fail-under=60]

Writes a per-file summary to ``.covgate.json`` and fails the run (exit 1 via
pytest's exitstatus hook) when total coverage < the gate.

Limitation (conservative): only in-process execution is measured. Modules
driven through subprocesses (the e2e entrypoint tests spawn `python -m
...training.entry`) report low here despite being covered — a subprocess
hook would require shadowing sitecustomize, which this environment uses for
accelerator-plugin registration, so the gate under-reports instead.
"""

import json
import os
import sys

PKG = "sagemaker_xgboost_container_tpu"

# Known blind spots (VERDICT r4 weak #7): modules whose tests drive them OUT
# of process, which sys.monitoring cannot see — their in-process percentages
# under-report real coverage. Enumerated here so the artifact carries its own
# exclusions; PARITY.md's gate section mirrors this list.
SUBPROCESS_SHADOWED = {
    "training/entry.py":
        "tests/test_training_e2e.py runs `python -m ...training.entry` in a "
        "subprocess (the SageMaker CMD contract)",
    "training/algorithm_train.py":
        "e2e subprocess entrypoint + 2-process jax.distributed workers "
        "(tests/util_multiprocess.py) carry the distributed branches",
    "parallel/distributed.py":
        "cluster bring-up runs in spawned 2-process workers "
        "(tests/test_parallel.py); only host-side helpers trace in-process",
    "data/record_pb2.py":
        "protoc-generated module: the class bodies execute at import; "
        "descriptor plumbing is exercised via data/recordio.py round-trips",
}
# an unreserved tool slot: coverage.py's sysmon mode owns the reserved
# COVERAGE_ID (1), so a distinct id avoids colliding if both are active
TOOL_ID = 4

_executed = {}     # filename -> set of line numbers hit
_executable = {}   # filename -> set of executable line numbers
_seen_codes = set()  # id(code) already registered via PY_START


def _want(filename):
    return (
        filename
        and os.sep + PKG + os.sep in filename
        and filename.endswith(".py")
        and os.sep + "tests" + os.sep not in filename
    )


def _register_code(code):
    """Record the executable lines of a code object (and its children)."""
    fn = code.co_filename
    if not _want(fn):
        return
    lines = _executable.setdefault(fn, set())
    for _start, _end, line in code.co_lines():
        if line is not None and line > 0:
            lines.add(line)
    for const in code.co_consts:
        if isinstance(const, type(code)):
            _register_code(const)


def _on_line(code, line_number):
    fn = code.co_filename
    if _want(fn):
        _executed.setdefault(fn, set()).add(line_number)
    # DISABLE either way: a measured line only needs recording once (set
    # membership), and unmeasured locations never need events — this is
    # what keeps the gate near-zero-overhead on hot loops
    return sys.monitoring.DISABLE


def _on_start(code, instruction_offset):
    # register once, then disable PY_START for this code object; LINE
    # events are governed separately so measurement continues
    if _want(code.co_filename) and id(code) not in _seen_codes:
        _seen_codes.add(id(code))
        _register_code(code)
    return sys.monitoring.DISABLE


def _start():
    mon = sys.monitoring
    mon.use_tool_id(TOOL_ID, "covgate")
    mon.register_callback(TOOL_ID, mon.events.LINE, _on_line)
    mon.register_callback(TOOL_ID, mon.events.PY_START, _on_start)
    mon.set_events(TOOL_ID, mon.events.LINE | mon.events.PY_START)


def _stop_and_report(fail_under):
    mon = sys.monitoring
    mon.set_events(TOOL_ID, 0)
    mon.free_tool_id(TOOL_ID)

    # files imported but never line-traced (or never imported at all) still
    # count their executable lines: walk the package tree for .py files and
    # compile any that monitoring never saw
    import py_compile  # noqa: F401  (documenting intent; we use compile())

    roots = set()
    for fn in list(_executable):
        i = fn.find(os.sep + PKG + os.sep)
        if i >= 0:
            roots.add(fn[: i + 1 + len(PKG)])
    for root in roots:
        for dirpath, _dirnames, filenames in os.walk(root):
            for name in filenames:
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                if path in _executable:
                    continue
                try:
                    with open(path, "rb") as f:
                        code = compile(f.read(), path, "exec")
                    _register_code(code)
                except (OSError, SyntaxError):
                    continue

    total_exec = total_hit = 0
    per_file = {}
    for fn, lines in sorted(_executable.items()):
        hit = len(_executed.get(fn, set()) & lines)
        total_exec += len(lines)
        total_hit += hit
        rel = fn[fn.find(PKG):] if PKG in fn else fn
        entry = {
            "lines": len(lines),
            "hit": hit,
            "pct": round(100.0 * hit / len(lines), 1) if lines else 100.0,
        }
        for suffix, why in SUBPROCESS_SHADOWED.items():
            if rel.endswith(suffix):
                entry["subprocess_shadowed"] = why
        per_file[rel] = entry
    pct = 100.0 * total_hit / total_exec if total_exec else 0.0
    doc = {
        "total_pct": round(pct, 2),
        "fail_under": fail_under,
        "total_lines": total_exec,
        "total_hit": total_hit,
        # the total is a FLOOR: these modules' real coverage lives in
        # subprocesses the monitor can't see (enumerated per file below)
        "blind_spots": sorted(
            rel for rel, e in per_file.items() if "subprocess_shadowed" in e
        ),
        "files": per_file,
    }
    try:
        with open(".covgate.json", "w") as f:
            json.dump(doc, f, indent=1)
    except OSError:
        pass
    sys.stderr.write(
        "covgate: {:.2f}% line coverage of {} ({}/{} lines; gate {}%)\n".format(
            pct, PKG, total_hit, total_exec, fail_under
        )
    )
    return pct


def pytest_addoption(parser):
    parser.addoption(
        "--covgate-fail-under",
        type=float,
        default=60.0,
        help="fail the run when package line coverage is below this percent",
    )


def pytest_configure(config):
    if not hasattr(sys, "monitoring"):  # pragma: no cover - py<3.12
        raise RuntimeError("covgate needs python >= 3.12 (sys.monitoring)")
    config._covgate_active = True
    _start()


def pytest_sessionfinish(session, exitstatus):
    config = session.config
    if not getattr(config, "_covgate_active", False):
        return
    config._covgate_active = False
    fail_under = config.getoption("--covgate-fail-under")
    pct = _stop_and_report(fail_under)
    if pct < fail_under and exitstatus == 0:
        sys.stderr.write(
            "covgate: FAILED the {}% gate\n".format(fail_under)
        )
        session.exitstatus = 1
