#!/bin/bash
# TPU recovery watcher: probe the tunneled chip every 150s; when it answers,
# run the per-stage dissection (pallas + knob A/Bs), the serving bench, the
# multiclass/ranking bench tasks, and a full re-probe of the histogram-impl
# matrix (refreshing bench_winner.json), then aggregate every run's final
# JSON line into .tpuwatch/latest.json — a single driver-visible artifact —
# and exit so the harness surfaces the results.
set -u
# GRAFT_REPO lets a frozen copy of this script (run from /tmp so mid-run
# edits to the repo file can't corrupt the incremental bash parse) find home
cd "${GRAFT_REPO:-/root/repo}"
OUT=.tpuwatch
mkdir -p "$OUT"
PROBE='import jax; print(jax.devices()); import jax.numpy as j; print((j.ones((128,128))@j.ones((128,128))).sum())'

echo "[watch] start $(date +%H:%M:%S)" >> "$OUT/watch.log"
while true; do
  if timeout 75 python -c "$PROBE" >> "$OUT/watch.log" 2>&1; then
    echo "[watch] chip healthy $(date +%H:%M:%S)" >> "$OUT/watch.log"
    break
  fi
  echo "[watch] still down $(date +%H:%M:%S)" >> "$OUT/watch.log"
  sleep 150
done

run() {  # run <timeout> <logfile> <env...> -- cmd...
  local t=$1 log=$2; shift 2
  echo "=== $* ($(date +%H:%M:%S))" >> "$OUT/$log"
  timeout "$t" env "$@" >> "$OUT/$log" 2>&1
  echo "=== rc=$? ($(date +%H:%M:%S))" >> "$OUT/$log"
  snapshot  # aggregate after every stage: a later wedge keeps earlier results
}

snapshot() {  # last JSON line of each log -> one driver-visible artifact
  python - "$OUT" <<'EOF'
import glob, json, os, sys, time
out = sys.argv[1]
doc = {"updated": time.strftime("%Y-%m-%dT%H:%M:%S"), "runs": {}}
for path in sorted(glob.glob(os.path.join(out, "*.log"))):
    name = os.path.basename(path)[: -len(".log")]
    if name == "watch":
        continue
    last = None
    with open(path, errors="replace") as f:
        for line in f:
            if line.startswith("{"):
                try:
                    last = json.loads(line)
                except ValueError:
                    pass
    doc["runs"][name] = last
tmp = os.path.join(out, ".latest.tmp")
with open(tmp, "w") as f:
    json.dump(doc, f, indent=1)
os.replace(tmp, os.path.join(out, "latest.json"))
EOF
}

run 1500 dissect_pallas.log GRAFT_HIST_IMPL=pallas python scripts/dissect.py
run 1200 dissect_novnodes.log GRAFT_HIST_IMPL=pallas GRAFT_HIST_VNODES=0 python scripts/dissect.py
run 1200 dissect_onehot.log GRAFT_HIST_IMPL=pallas GRAFT_ROUTE_IMPL=onehot GRAFT_TOTALS_IMPL=pallas python scripts/dissect.py
# the TPU default flipped to totals=onehot in r4: pin totals=segment once so
# the r2-suspect segment_sum stage is still observable/attributable on chip
run 1200 dissect_totals_segment.log GRAFT_HIST_IMPL=pallas GRAFT_TOTALS_IMPL=segment python scripts/dissect.py
run 900 bench_serve.log python bench_serve.py
# BENCH_TIMEOUT_S grown with the 8-probe matrix (147s/probe cap vs 97s at
# the 1200 default) — still inside the 1800s external timeout
run 1800 bench_reprobe.log BENCH_REPROBE=1 BENCH_TIMEOUT_S=1600 python bench.py
run 1500 bench_multiclass.log GRAFT_HIST_IMPL=pallas BENCH_TASK=multiclass python bench.py
run 1500 bench_ranking.log GRAFT_HIST_IMPL=pallas BENCH_TASK=ranking python bench.py
# leaf-wise at LightGBM scale (VERDICT r3 #7): smaller row count + few
# rounds — the 254-step unrolled tree is a heavy compile on the tunnel
run 1500 bench_lossguide.log GRAFT_HIST_IMPL=pallas BENCH_TASK=lossguide BENCH_ROWS=250000 BENCH_ROUNDS_N=4 BENCH_WARMUP=1 python bench.py
echo "[watch] done $(date +%H:%M:%S)" >> "$OUT/watch.log"
