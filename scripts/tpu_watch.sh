#!/bin/bash
# TPU recovery watcher: probe the tunneled chip every 150s; when it answers,
# run the per-stage dissection (pallas + route A/B) and the serving bench,
# then exit so the harness surfaces the results. Artifacts in .tpuwatch/.
set -u
# GRAFT_REPO lets a frozen copy of this script (run from /tmp so mid-run
# edits to the repo file can't corrupt the incremental bash parse) find home
cd "${GRAFT_REPO:-/root/repo}"
OUT=.tpuwatch
mkdir -p "$OUT"
PROBE='import jax; print(jax.devices()); import jax.numpy as j; print((j.ones((128,128))@j.ones((128,128))).sum())'

echo "[watch] start $(date +%H:%M:%S)" >> "$OUT/watch.log"
while true; do
  if timeout 75 python -c "$PROBE" >> "$OUT/watch.log" 2>&1; then
    echo "[watch] chip healthy $(date +%H:%M:%S)" >> "$OUT/watch.log"
    break
  fi
  echo "[watch] still down $(date +%H:%M:%S)" >> "$OUT/watch.log"
  sleep 150
done

run() {  # run <timeout> <logfile> <env...> -- cmd...
  local t=$1 log=$2; shift 2
  echo "=== $* ($(date +%H:%M:%S))" >> "$OUT/$log"
  timeout "$t" env "$@" >> "$OUT/$log" 2>&1
  echo "=== rc=$? ($(date +%H:%M:%S))" >> "$OUT/$log"
}

run 1500 dissect_pallas.log GRAFT_HIST_IMPL=pallas python scripts/dissect.py
run 1200 dissect_novnodes.log GRAFT_HIST_IMPL=pallas GRAFT_HIST_VNODES=0 python scripts/dissect.py
run 1200 dissect_onehot.log GRAFT_HIST_IMPL=pallas GRAFT_ROUTE_IMPL=onehot GRAFT_TOTALS_IMPL=pallas python scripts/dissect.py
run 900 bench_serve.log python bench_serve.py
run 1500 bench_multiclass.log GRAFT_HIST_IMPL=pallas BENCH_TASK=multiclass python bench.py
run 1500 bench_ranking.log GRAFT_HIST_IMPL=pallas BENCH_TASK=ranking python bench.py
echo "[watch] done $(date +%H:%M:%S)" >> "$OUT/watch.log"
