#!/bin/bash
# TPU recovery watcher: probe the tunneled chip every 150s; when it answers,
# run the per-stage dissection (pallas + knob A/Bs), the serving bench, the
# multiclass/ranking bench tasks, and a full re-probe of the histogram-impl
# matrix (refreshing bench_winner.json), then aggregate every run's final
# JSON line into .tpuwatch/latest.json — a single driver-visible artifact —
# and exit so the harness surfaces the results.
set -u
# GRAFT_REPO lets a frozen copy of this script (run from /tmp so mid-run
# edits to the repo file can't corrupt the incremental bash parse) find home
cd "${GRAFT_REPO:-/root/repo}"
OUT=.tpuwatch
mkdir -p "$OUT"
# Phased probe taxonomy (VERDICT r4 #1): stage 1 is a bare backend init
# (jax.devices() only — no compile, no dispatch) so the artifact says WHAT is
# broken: rc=124 on stage 1 => backend init itself hangs ("tunnel wedged");
# a nonzero non-timeout rc => libtpu/plugin raised during init (captured
# stderr tail); stage-2 failures with stage 1 ok => compile/execute path.
INIT_PROBE='import jax; print(",".join(str(d) for d in jax.devices()))'
COMPUTE_PROBE='import jax; import jax.numpy as j; print((j.ones((128,128))@j.ones((128,128))).sum())'

probe_taxonomy() {  # one phased probe; appends a JSON line to probes.jsonl
  local ts init compute err devices rc
  ts=$(date +%Y-%m-%dT%H:%M:%S)
  devices=$(timeout -k 15 60 python -c "$INIT_PROBE" 2>"$OUT/.probe_err"); rc=$?
  err=""
  if [ $rc -eq 0 ]; then init=ok
  elif [ $rc -eq 124 ]; then init=hang; err="backend init (jax.devices) exceeded 60s — tunnel wedged"
  else init=error; err=$(cat "$OUT/.probe_err"); fi
  compute=skipped
  if [ "$init" = ok ]; then
    if timeout -k 15 75 python -c "$COMPUTE_PROBE" >/dev/null 2>"$OUT/.probe_err"; then
      compute=ok
    else
      rc=$?
      if [ $rc -eq 124 ]; then compute=hang; err="matmul dispatch exceeded 75s with backend init ok"
      else compute=error; err=$(cat "$OUT/.probe_err"); fi
    fi
  fi
  python - "$OUT" "$ts" "$init" "$compute" "$err" "$devices" <<'EOF'
import json, os, sys
out, ts, init, compute, err, devices = sys.argv[1:7]
rec = {"t": ts, "init": init, "compute": compute}
if init == "ok" and devices.strip():
    rec["devices"] = devices.strip().splitlines()[-1][:200]
if err.strip():
    rec["err"] = err.strip().splitlines()[-1][:400]
with open(os.path.join(out, "probes.jsonl"), "a") as f:
    f.write(json.dumps(rec) + "\n")
# rolling summary: driver-visible taxonomy even if the chip never recovers
counts, first, last = {}, None, rec
with open(os.path.join(out, "probes.jsonl")) as f:
    for line in f:
        try:
            r = json.loads(line)
        except ValueError:  # truncated append (crash/kill mid-write)
            continue
        key = r["init"] if r["init"] != "ok" else "init_ok_compute_" + r["compute"]
        counts[key] = counts.get(key, 0) + 1
        first = first or r
doc = {"updated": ts, "probes": sum(counts.values()), "taxonomy": counts,
       "first": first, "last": last}
tmp = os.path.join(out, ".probe_summary.tmp")
with open(tmp, "w") as f:
    json.dump(doc, f, indent=1)
os.replace(tmp, os.path.join(out, "probe_summary.json"))
EOF
  [ "$compute" = ok ]
}

echo "[watch] start $(date +%H:%M:%S)" >> "$OUT/watch.log"
# rotate the probe record at start: the summary must describe THIS run's
# outage, not accumulate prior rounds' probes (.tpuwatch persists)
if [ -s "$OUT/probes.jsonl" ]; then
  mv "$OUT/probes.jsonl" "$OUT/probes.prev.jsonl"
fi
while true; do
  if probe_taxonomy; then
    echo "[watch] chip healthy $(date +%H:%M:%S)" >> "$OUT/watch.log"
    break
  fi
  echo "[watch] still down $(date +%H:%M:%S) ($(tail -n1 "$OUT/probes.jsonl"))" >> "$OUT/watch.log"
  sleep 150
done

run() {  # run <timeout> <logfile> <env...> -- cmd...
  local t=$1 log=$2; shift 2
  echo "=== $* ($(date +%H:%M:%S))" >> "$OUT/$log"
  timeout "$t" env "$@" >> "$OUT/$log" 2>&1
  echo "=== rc=$? ($(date +%H:%M:%S))" >> "$OUT/$log"
  snapshot  # aggregate after every stage: a later wedge keeps earlier results
}

snapshot() {  # last JSON line of each log -> one driver-visible artifact
  python - "$OUT" <<'EOF'
import glob, json, os, sys, time
out = sys.argv[1]
doc = {"updated": time.strftime("%Y-%m-%dT%H:%M:%S"), "runs": {}}
for path in sorted(glob.glob(os.path.join(out, "*.log"))):
    name = os.path.basename(path)[: -len(".log")]
    if name == "watch":
        continue
    last = None
    with open(path, errors="replace") as f:
        for line in f:
            if line.startswith("{"):
                try:
                    last = json.loads(line)
                except ValueError:
                    pass
    doc["runs"][name] = last
tmp = os.path.join(out, ".latest.tmp")
with open(tmp, "w") as f:
    json.dump(doc, f, indent=1)
os.replace(tmp, os.path.join(out, "latest.json"))
EOF
}

run 1500 dissect_pallas.log GRAFT_HIST_IMPL=pallas python scripts/dissect.py
run 1200 dissect_novnodes.log GRAFT_HIST_IMPL=pallas GRAFT_HIST_VNODES=0 python scripts/dissect.py
run 1200 dissect_onehot.log GRAFT_HIST_IMPL=pallas GRAFT_ROUTE_IMPL=onehot GRAFT_TOTALS_IMPL=pallas python scripts/dissect.py
# the TPU default flipped to totals=onehot in r4: pin totals=segment once so
# the r2-suspect segment_sum stage is still observable/attributable on chip
run 1200 dissect_totals_segment.log GRAFT_HIST_IMPL=pallas GRAFT_TOTALS_IMPL=segment python scripts/dissect.py
run 900 bench_serve.log python bench_serve.py
# BENCH_TIMEOUT_S grown with the 8-probe matrix (147s/probe cap vs 97s at
# the 1200 default) — still inside the 1800s external timeout
run 1800 bench_reprobe.log BENCH_REPROBE=1 BENCH_TIMEOUT_S=1600 python bench.py
run 1500 bench_multiclass.log GRAFT_HIST_IMPL=pallas BENCH_TASK=multiclass python bench.py
run 1500 bench_ranking.log GRAFT_HIST_IMPL=pallas BENCH_TASK=ranking python bench.py
# leaf-wise at LightGBM scale (VERDICT r3 #7): smaller row count + few
# rounds — the 254-step unrolled tree is a heavy compile on the tunnel
run 1500 bench_lossguide.log GRAFT_HIST_IMPL=pallas BENCH_TASK=lossguide BENCH_ROWS=250000 BENCH_ROUNDS_N=4 BENCH_WARMUP=1 python bench.py
echo "[watch] done $(date +%H:%M:%S)" >> "$OUT/watch.log"
