#!/bin/bash
# Build the shipping image and run the SageMaker contract against it
# end-to-end: fabricate the /opt/ml filesystem a training job receives,
# `docker run … train` on abalone, assert the model artifact, then
# `docker run … serve` and POST /invocations. This is the repo's analog of
# the reference's local_mode harness (reference test/utils/local_mode.py:
# 371-396 fabricates the same config tree; :477-557 runs the built image).
#
# Needs Docker (or podman via DOCKER=podman) + network for the pip installs
# inside the build. CPU-only by default (JAX_SPEC=jax); pass
# JAX_SPEC="jax[tpu]" to build the real TPU image.
set -euo pipefail
REPO="$(cd "$(dirname "$0")/.." && pwd)"
DOCKER="${DOCKER:-docker}"
TAG="${IMAGE_TAG:-sagemaker-xgboost-tpu:smoke}"
PORT="${SMOKE_PORT:-18081}"
DATA_SRC="${ABALONE_DATA:-/root/reference/test/resources/abalone/data}"

command -v "$DOCKER" >/dev/null || { echo "SKIP: $DOCKER not installed"; exit 75; }

echo "== build =="
"$DOCKER" build -f "$REPO/docker/Dockerfile.tpu" \
  --build-arg JAX_SPEC="${JAX_SPEC:-jax}" -t "$TAG" "$REPO"

WORK="$(mktemp -d)"
CID=""
trap '[ -n "$CID" ] && "$DOCKER" rm -f "$CID" >/dev/null 2>&1 || true; rm -rf "$WORK"' EXIT
mkdir -p "$WORK"/{input/config,input/data/train,input/data/validation,model,output/data}

cat > "$WORK/input/config/hyperparameters.json" <<'JSON'
{"num_round": "10", "objective": "reg:squarederror", "max_depth": "4", "eval_metric": "rmse"}
JSON
cat > "$WORK/input/config/inputdataconfig.json" <<'JSON'
{"train": {"ContentType": "libsvm", "TrainingInputMode": "File", "S3DistributionType": "FullyReplicated"},
 "validation": {"ContentType": "libsvm", "TrainingInputMode": "File", "S3DistributionType": "FullyReplicated"}}
JSON
cat > "$WORK/input/config/resourceconfig.json" <<'JSON'
{"current_host": "algo-1", "hosts": ["algo-1"]}
JSON
cp "$DATA_SRC"/train/* "$WORK/input/data/train/"
cp "$DATA_SRC"/validation/* "$WORK/input/data/validation/"

echo "== train (in-image) =="
# only the /opt/ml mount + CMD "train": the image must derive the SM_* env
# itself (sagemaker-containers parity — entry.derive_sm_env)
"$DOCKER" run --rm -v "$WORK:/opt/ml" -e JAX_PLATFORMS=cpu "$TAG" train
test -f "$WORK/model/xgboost-model" || { echo "FAIL: no model artifact"; exit 1; }

echo "== serve (in-image) =="
CID="$("$DOCKER" run -d -p "$PORT:8080" -v "$WORK/model:/opt/ml/model" \
  -e JAX_PLATFORMS=cpu "$TAG" serve)"
for i in $(seq 1 60); do
  curl -sf "localhost:$PORT/ping" >/dev/null 2>&1 && break
  sleep 1
  [ "$i" = 60 ] && { echo "FAIL: serve never became healthy"; "$DOCKER" logs "$CID"; exit 1; }
done
PRED="$(curl -s -X POST "localhost:$PORT/invocations" -H "Content-Type: text/libsvm" \
  -d "1:2 2:0.74 3:0.6 4:0.195 5:1.974 6:0.598 7:0.4085 8:0.71")"
echo "prediction: $PRED"
python3 - "$PRED" <<'EOF'
import sys
v = float(sys.argv[1].strip())
assert 0.0 < v < 30.0, v  # abalone ring count band
EOF
echo "IMAGE SMOKE OK"
