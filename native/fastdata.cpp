// Native data-plane: high-throughput LIBSVM tokenizer.
//
// The reference container's heavy ingestion ran through native code too
// (libxgboost's parsers + MLIO, SURVEY.md §2.2): pure-Python tokenization of
// multi-GB libsvm shards would dominate job start time. This library performs
// the two-pass parse (count, then fill preallocated numpy buffers) with no
// allocation in the hot loop; Python binds it via ctypes
// (sagemaker_xgboost_container_tpu/data/native.py) with a pure-Python
// fallback when no compiler is available.
//
// Accepted grammar per line (same as data/readers.py:parse_libsvm_text):
//   <label>(:<weight>) (qid:<q>) (<idx>:<val>)*   [# comment]
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Cursor {
    const char* p;
    const char* end;
};

inline void skip_spaces(Cursor& c) {
    while (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\r')) ++c.p;
}

inline bool at_line_end(const Cursor& c) {
    return c.p >= c.end || *c.p == '\n' || *c.p == '#';
}

inline void skip_line(Cursor& c) {
    while (c.p < c.end && *c.p != '\n') ++c.p;
    if (c.p < c.end) ++c.p;
}

// strtof/strtoll on a bounded, non-null-terminated buffer: the buffer handed
// to us always ends with '\n' or we copy the tail, so direct strtof is safe
// in practice; we bound-check via endptr anyway.
inline bool parse_float(Cursor& c, float* out) {
    char* endp = nullptr;
    *out = strtof(c.p, &endp);
    if (endp == c.p || endp > c.end) return false;
    c.p = endp;
    return true;
}

inline bool parse_int(Cursor& c, int64_t* out) {
    char* endp = nullptr;
    *out = strtoll(c.p, &endp, 10);
    if (endp == c.p || endp > c.end) return false;
    c.p = endp;
    return true;
}

}  // namespace

extern "C" {

struct LibsvmInfo {
    int64_t n_rows;
    int64_t nnz;
    int64_t max_index;
    int32_t has_weights;
    int32_t has_qids;
    int64_t error_line;  // 1-based line of first parse error, 0 if ok
};

// Pass 1: validate + count rows / non-zeros.
int libsvm_count(const char* buf, int64_t len, LibsvmInfo* info) {
    Cursor c{buf, buf + len};
    info->n_rows = 0;
    info->nnz = 0;
    info->max_index = -1;
    info->has_weights = 0;
    info->has_qids = 0;
    info->error_line = 0;
    int64_t line_no = 0;
    while (c.p < c.end) {
        ++line_no;
        skip_spaces(c);
        if (at_line_end(c)) { skip_line(c); continue; }
        float label;
        if (!parse_float(c, &label)) { info->error_line = line_no; return 1; }
        if (c.p < c.end && *c.p == ':') {
            ++c.p;
            float w;
            if (!parse_float(c, &w)) { info->error_line = line_no; return 1; }
            info->has_weights = 1;
        }
        while (true) {
            skip_spaces(c);
            if (at_line_end(c)) break;
            if (c.end - c.p >= 4 && memcmp(c.p, "qid:", 4) == 0) {
                c.p += 4;
                int64_t q;
                if (!parse_int(c, &q)) { info->error_line = line_no; return 1; }
                info->has_qids = 1;
                continue;
            }
            int64_t idx;
            if (!parse_int(c, &idx) || c.p >= c.end || *c.p != ':') {
                info->error_line = line_no;
                return 1;
            }
            ++c.p;
            float v;
            if (!parse_float(c, &v)) { info->error_line = line_no; return 1; }
            if (idx > info->max_index) info->max_index = idx;
            ++info->nnz;
        }
        ++info->n_rows;
        skip_line(c);
    }
    return 0;
}

// Pass 2: fill preallocated buffers (sizes from pass 1).
int libsvm_fill(const char* buf, int64_t len, float* labels, float* weights,
                int64_t* qids, int64_t* indices, float* values, int64_t* indptr) {
    Cursor c{buf, buf + len};
    int64_t row = 0;
    int64_t k = 0;
    indptr[0] = 0;
    while (c.p < c.end) {
        skip_spaces(c);
        if (at_line_end(c)) { skip_line(c); continue; }
        float label;
        if (!parse_float(c, &label)) return 1;
        labels[row] = label;
        weights[row] = 1.0f;
        if (qids) qids[row] = 0;
        if (c.p < c.end && *c.p == ':') {
            ++c.p;
            float w;
            if (!parse_float(c, &w)) return 1;
            weights[row] = w;
        }
        while (true) {
            skip_spaces(c);
            if (at_line_end(c)) break;
            if (c.end - c.p >= 4 && memcmp(c.p, "qid:", 4) == 0) {
                c.p += 4;
                int64_t q;
                if (!parse_int(c, &q)) return 1;
                if (qids) qids[row] = q;
                continue;
            }
            int64_t idx;
            if (!parse_int(c, &idx) || *c.p != ':') return 1;
            ++c.p;
            float v;
            if (!parse_float(c, &v)) return 1;
            indices[k] = idx;
            values[k] = v;
            ++k;
        }
        ++row;
        indptr[row] = k;
        skip_line(c);
    }
    return 0;
}

// ---------------------------------------------------------------- parallel
//
// Multi-threaded two-pass parse: the buffer splits into nchunks
// newline-aligned chunks (boundaries derived identically in count and fill,
// so the passes always agree), each chunk parsed independently. Python
// prefix-sums the per-chunk counts into row/nnz bases for the fill. Error
// lines are chunk-local; the (rare) error path re-runs the single-threaded
// counter for an exact global line number.

static int64_t chunk_start(const char* buf, int64_t len, int32_t nchunks,
                           int32_t i) {
    if (i <= 0) return 0;
    if (i >= nchunks) return len;
    int64_t pos = len * static_cast<int64_t>(i) / nchunks;
    const char* nl =
        static_cast<const char*>(memchr(buf + pos, '\n', len - pos));
    return nl ? (nl - buf) + 1 : len;
}

extern "C" int libsvm_count_mt(const char* buf, int64_t len, int32_t nchunks,
                               LibsvmInfo* merged, LibsvmInfo* per_chunk) {
    std::vector<std::thread> ts;
    ts.reserve(nchunks);
    for (int32_t i = 0; i < nchunks; ++i) {
        int64_t s = chunk_start(buf, len, nchunks, i);
        int64_t e = chunk_start(buf, len, nchunks, i + 1);
        ts.emplace_back([buf, s, e, i, per_chunk]() {
            libsvm_count(buf + s, e - s, &per_chunk[i]);
        });
    }
    for (auto& t : ts) t.join();
    merged->n_rows = 0;
    merged->nnz = 0;
    merged->max_index = -1;
    merged->has_weights = 0;
    merged->has_qids = 0;
    merged->error_line = 0;
    for (int32_t i = 0; i < nchunks; ++i) {
        const LibsvmInfo& ci = per_chunk[i];
        if (ci.error_line) {
            merged->error_line = ci.error_line;  // chunk-local; caller refines
            return 1;
        }
        merged->n_rows += ci.n_rows;
        merged->nnz += ci.nnz;
        if (ci.max_index > merged->max_index) merged->max_index = ci.max_index;
        merged->has_weights |= ci.has_weights;
        merged->has_qids |= ci.has_qids;
    }
    return 0;
}

extern "C" int libsvm_fill_mt(const char* buf, int64_t len, int32_t nchunks,
                              const LibsvmInfo* per_chunk, float* labels,
                              float* weights, int64_t* qids, int64_t* indices,
                              float* values, int64_t* indptr) {
    std::vector<int64_t> row_base(nchunks + 1, 0), nnz_base(nchunks + 1, 0);
    for (int32_t i = 0; i < nchunks; ++i) {
        row_base[i + 1] = row_base[i] + per_chunk[i].n_rows;
        nnz_base[i + 1] = nnz_base[i] + per_chunk[i].nnz;
    }
    std::vector<std::thread> ts;
    std::vector<int> rcs(nchunks, 0);
    ts.reserve(nchunks);
    indptr[0] = 0;
    for (int32_t i = 0; i < nchunks; ++i) {
        int64_t s = chunk_start(buf, len, nchunks, i);
        int64_t e = chunk_start(buf, len, nchunks, i + 1);
        int64_t rb = row_base[i], nb = nnz_base[i];
        ts.emplace_back([=, &rcs]() {
            // fill into a chunk-local indptr, then publish entries
            // 1..n_rows rebased by nb: entry rb (== previous chunk's last)
            // belongs to the previous chunk — writing it here would race
            int64_t n_rows = per_chunk[i].n_rows;
            std::vector<int64_t> local(n_rows + 1);
            rcs[i] = libsvm_fill(buf + s, e - s, labels + rb, weights + rb,
                                 qids ? qids + rb : nullptr, indices + nb,
                                 values + nb, local.data());
            if (rcs[i] == 0) {
                for (int64_t r = 1; r <= n_rows; ++r)
                    indptr[rb + r] = local[r] + nb;
            }
        });
    }
    for (auto& t : ts) t.join();
    for (int32_t i = 0; i < nchunks; ++i)
        if (rcs[i]) return 1;
    return 0;
}

// Scalar forest traversal for tiny serving payloads: the reference serves
// single-row /invocations through libxgboost's C++ predictor
// (serve_utils.py:244-250); the numpy twin (ops/predict.py
// _leaf_nodes_impl, xp=np) pays ~0.3 ms of per-op interpreter overhead for
// a 100-tree forest where this loop pays ~2 us. Semantics mirror
// _leaf_nodes_impl EXACTLY (NaN-missing follows default_left; numerical
// goes right on v >= threshold; categorical goes right when the truncated
// int category's bit is set, invalid (v<0 or v>=32*W) goes left; leaves
// self-loop, padded nodes are never visited). Arrays are the forest's
// stacked [T, N] layout; cat_split/cat_mask may be NULL. out is [n, T]
// per-tree leaf VALUES (group summing stays in Python, where tree_info
// lives).
extern "C" int forest_leaf_values(
    const int32_t* feature, const float* threshold,
    const uint8_t* default_left, const int32_t* left, const int32_t* right,
    const uint8_t* is_leaf, const float* leaf_value,
    const uint8_t* cat_split, const uint32_t* cat_mask,
    int64_t T, int64_t N, int64_t W,
    const float* x, int64_t n, int64_t d, int32_t depth, float* out) {
  const float max_cat = (float)(W * 32);
  for (int64_t i = 0; i < n; ++i) {
    const float* row = x + i * d;
    for (int64_t t = 0; t < T; ++t) {
      const int64_t base = t * N;
      int32_t node = 0;
      for (int32_t step = 0; step < depth; ++step) {
        if (is_leaf[base + node]) break;
        const float v = row[feature[base + node]];
        const bool miss = v != v;  // NaN
        const bool dfl = default_left[base + node] != 0;
        bool go_right;
        if (cat_split != nullptr && cat_split[base + node]) {
          if (miss) {
            go_right = !dfl;
          } else if (v < 0.0f || v >= max_cat) {  // invalid category -> left
            go_right = false;
          } else {
            const int32_t c = (int32_t)v;  // truncation, matches astype(int32)
            const uint32_t word = cat_mask[(base + node) * W + (c >> 5)];
            go_right = ((word >> (c & 31)) & 1u) != 0u;
          }
        } else {
          go_right = miss ? !dfl : (v >= threshold[base + node]);
        }
        node = go_right ? right[base + node] : left[base + node];
      }
      out[i * T + t] = leaf_value[base + node];
    }
  }
  return 0;
}

}  // extern "C"
