#!/usr/bin/env python
"""Benchmark: boosting rounds/sec of the XLA histogram tree builder.

Measures steady-state boosting throughput on a synthetic Higgs-like binary
classification task (BASELINE.md config #2: dense numeric features,
binary:logistic, hist). Prints JSON result lines; the LAST line is the
authoritative result:

    {"metric": ..., "value": N, "unit": "rounds/sec", "vs_baseline": N}

vs_baseline is measured against the north-star target of 5 boosting
rounds/sec (BASELINE.json) — the reference publishes no numbers of its own
(BASELINE.md: published = {}).

Robustness contract (the TPU tunnel in this environment can wedge
indefinitely — docs/ROUND2_STATE.md):
  * a 90s backend pre-check runs before anything expensive; a wedged tunnel
    costs ~90s, not the whole budget, before the labeled CPU fallback
  * the winning probe config is persisted to bench_winner.json; later runs
    skip the probe matrix and measure the winner directly (re-probe with
    BENCH_REPROBE=1)
  * every intermediate success is printed immediately, so an external kill
    at any point still leaves a parseable best-so-far line on stdout
  * two consecutive probe timeouts trip a circuit breaker (a wedged tunnel
    fails every probe the same way — stop paying for it)
  * the whole internal budget (BENCH_TIMEOUT_S, default 1200s) is sized to
    fit inside plausible external driver budgets
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

BENCH_TIMEOUT_S = int(os.getenv("BENCH_TIMEOUT_S", "1200"))
WINNER_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_winner.json"
)
# knobs that define a measurement config; everything else inherits
_CONFIG_KEYS = (
    "GRAFT_HIST_IMPL",
    "GRAFT_HIST_MM_PREC",
    "GRAFT_HIST_VNODES",
    "GRAFT_ROUTE_IMPL",
    "GRAFT_TOTALS_IMPL",
    "GRAFT_HIST_COMM",
    "GRAFT_HIST_OVERLAP",
    "BENCH_MESH_SHAPE",
    "BENCH_ROUNDS_PER_DISPATCH",
)


# set when the accelerator-backend pre-check fails or wedges: every result
# line carries the captured reason instead of silently reading "CPU" —
# BASELINE.md: every TPU probe so far wedged at init with no recorded cause
_backend_init_error = None
# the pre-check's SUCCESS-path facts (platform, device count, per-device
# memory_stats) — a healthy TPU run should be as diagnosable as a wedged one
_backend_probe_info = None


def _emit(doc):
    """Print a result line immediately (stdout is the driver artifact; the
    last parseable line wins, so best-so-far lines are safe to emit)."""
    if _backend_init_error and "backend_init_error" not in doc:
        doc = dict(doc, backend_init_error=_backend_init_error)
    if _backend_probe_info and "backend_info" not in doc:
        doc = dict(doc, backend_info=_backend_probe_info)
    print(json.dumps(doc), flush=True)


def _result_doc(value, metric, note=""):
    return {
        "metric": metric + ((" " + note) if note else ""),
        "value": round(value, 3),
        "unit": "rounds/sec",
        "vs_baseline": round(value / NORTH_STAR_ROUNDS_PER_SEC, 3),
    }


def _run_child(env_extra, timeout):
    """One supervised child run -> (parsed JSON dict, None) or (None, note)."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env.update(env_extra)
    try:
        result = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        for line in reversed(result.stdout.splitlines()):
            if line.startswith("{"):
                return json.loads(line), None
        err_tail = " | ".join(result.stderr.strip().splitlines()[-3:])[-400:]
        return None, "child produced no result (rc={}): {}".format(
            result.returncode, err_tail
        )
    except subprocess.TimeoutExpired:
        return None, "child timed out after {}s".format(timeout)


def _backend_healthy(timeout):
    """Cheap bounded pre-check: can the accelerator backend answer a tiny
    matmul within `timeout`? A wedged tunnel hangs jax.devices() forever —
    pay the bounded probe here instead of a full probe budget per config.

    Returns ``(healthy, n_devices, error)``: the device count decides
    whether the GRAFT_HIST_COMM probe column is meaningful (collectives
    need a mesh); ``error`` is None when healthy, else a dict with the
    captured failure text and the elapsed probe seconds — recorded in the
    BENCH JSON as ``backend_init_error`` so a wedged init finally leaves a
    reason behind instead of a silent CPU fallback."""
    code = (
        "import jax, jax.numpy as j, json\n"
        "ds = jax.devices()\n"
        "print('DEVICES', len(ds))\n"
        "print(float((j.ones((128,128))@j.ones((128,128))).sum()))\n"
        "info = {'platform': ds[0].platform,"
        " 'device_kind': getattr(ds[0], 'device_kind', 'unknown'),"
        " 'n_devices': len(ds), 'memory_stats': []}\n"
        "for d in ds:\n"
        "    try:\n"
        "        s = d.memory_stats() or {}\n"
        "    except Exception:\n"
        "        s = {}\n"
        "    info['memory_stats'].append({'id': d.id,"
        " 'bytes_in_use': int(s.get('bytes_in_use', 0)),"
        " 'bytes_limit': int(s.get('bytes_limit', 0))})\n"
        "print('BACKEND_INFO', json.dumps(info))\n"
    )
    t0 = time.monotonic()
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return False, 0, {
            "error": "backend init probe timed out (wedged tunnel?)",
            "elapsed_s": round(time.monotonic() - t0, 1),
        }
    n_devices = 1
    global _backend_probe_info
    for line in r.stdout.splitlines():
        if line.startswith("DEVICES "):
            n_devices = int(line.split()[1])
        elif line.startswith("BACKEND_INFO ") and r.returncode == 0:
            try:
                _backend_probe_info = json.loads(line.split(" ", 1)[1])
                _backend_probe_info["probe_s"] = round(
                    time.monotonic() - t0, 1
                )
            except (ValueError, IndexError):
                pass
    if r.returncode == 0:
        return True, n_devices, None
    tail = " | ".join(r.stderr.strip().splitlines()[-3:])[-400:]
    return False, n_devices, {
        "error": "backend init probe rc={}: {}".format(r.returncode, tail),
        "elapsed_s": round(time.monotonic() - t0, 1),
    }


def _code_fingerprint():
    """Hash of the performance-relevant sources (compute kernels + this
    bench). A winner measured under a different fingerprint may predate the
    current optimization wave, so it must be re-probed, not re-measured
    (VERDICT r3 weak #3); unrelated commits (docs, serving, tests) keep the
    cache warm. Returns None when the sources are unreadable (undecidable)."""
    import hashlib

    here = os.path.dirname(os.path.abspath(__file__))
    pkg = os.path.join(here, "sagemaker_xgboost_container_tpu")
    paths = [os.path.abspath(__file__)]
    for sub in ("ops", "models"):
        d = os.path.join(pkg, sub)
        if os.path.isdir(d):
            paths += [
                os.path.join(d, f) for f in os.listdir(d) if f.endswith(".py")
            ]
    paths.append(os.path.join(pkg, "data", "binning.py"))
    h = hashlib.sha256()
    found = False
    for p in sorted(paths):
        try:
            with open(p, "rb") as f:
                # path relative to the repo root: identical code at a
                # different checkout path must fingerprint identically
                h.update(os.path.relpath(p, here).encode())
                h.update(f.read())
            found = True
        except OSError:
            continue
    return h.hexdigest()[:12] if found else None


def _load_winner():
    """-> (label, env, stale). ``stale`` means the perf-relevant code changed
    since the winner was measured (or the doc predates fingerprinting): the
    config may under-report the current code — callers should re-probe."""
    try:
        with open(WINNER_FILE) as f:
            doc = json.load(f)
        env = {k: str(v) for k, v in doc.get("env", {}).items() if k in _CONFIG_KEYS}
        if env.get("GRAFT_HIST_IMPL"):
            fp = _code_fingerprint()
            stale = fp is not None and doc.get("code") != fp
            return doc.get("label", "winner"), env, stale
    except (OSError, ValueError, KeyError):
        pass
    return None, None, False


def _save_winner(label, env, value, source):
    try:
        with open(WINNER_FILE, "w") as f:
            json.dump(
                {
                    "label": label,
                    "env": {k: v for k, v in env.items() if k in _CONFIG_KEYS},
                    "value": round(value, 3),
                    "source": source,
                    "code": _code_fingerprint(),
                },
                f,
                indent=1,
            )
            f.write("\n")
    except OSError as e:
        sys.stderr.write("could not persist winner: {}\n".format(e))


def _cpu_fallback(deadline, note):
    """Honest, labeled CPU number — better than a 0.0 (same policy since r1)."""
    remaining = deadline - time.monotonic()
    if remaining >= 60:
        doc, err = _run_child(
            {"JAX_PLATFORMS": "cpu", "GRAFT_HIST_IMPL": "flat"},
            int(min(remaining, 900)),
        )
        if doc:
            doc["metric"] = "{} [CPU FALLBACK - {}]".format(
                doc["metric"], note[:160]
            )
            _emit(doc)
            return True
        note = err or note
    _emit(
        {
            "metric": "boosting rounds/sec (synthetic Higgs-like) — FAILED: "
            + note,
            "value": 0.0,
            "unit": "rounds/sec",
            "vs_baseline": 0.0,
        }
    )
    return False


def _probe_matrix(deadline, n_devices=1):
    """A/B the histogram impls, each in its own supervised child. Returns
    (best_label, best_env, best_value, results, note)."""
    probe_timeout = int(os.getenv("BENCH_PROBE_TIMEOUT_S", "600"))
    # impl x operand-precision x lowering matrix (bf16 operands are
    # quality-validated: matches f32 val-logloss/auc on the bench task,
    # BASELINE.md). Every knob pinned in every entry: an inherited env
    # would otherwise silently collapse the A/B. vnodes=0 probes guard
    # against the virtual-node packing regressing on real hardware.
    base = {
        "GRAFT_HIST_MM_PREC": "bf16x2",
        "GRAFT_HIST_VNODES": "1",
        "GRAFT_ROUTE_IMPL": "gather",
        "GRAFT_TOTALS_IMPL": "segment",
        "GRAFT_HIST_COMM": "psum",
        "GRAFT_HIST_OVERLAP": "1",
        # empty = the auto 1-D data mesh; pinned so an inherited 2-D shape
        # can't silently reshape every other probe's mesh
        "BENCH_MESH_SHAPE": "",
        # pinned to the historical child default so the impl probes stay
        # comparable across rounds; the rounds_per_dispatch column below
        # A/Bs the fused-dispatch depth explicitly
        "BENCH_ROUNDS_PER_DISPATCH": "10",
    }
    configs = [
        ("flat", dict(base, GRAFT_HIST_IMPL="flat")),
        ("matmul", dict(base, GRAFT_HIST_IMPL="matmul")),
        ("pallas", dict(base, GRAFT_HIST_IMPL="pallas")),
        (
            "pallas,vnodes=0",
            dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_HIST_VNODES="0"),
        ),
        (
            "pallas,prec=bf16",
            dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_HIST_MM_PREC="bf16"),
        ),
        (
            "pallas,route=onehot",
            dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_ROUTE_IMPL="onehot"),
        ),
        (
            "pallas,totals=pallas",
            dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_TOTALS_IMPL="pallas"),
        ),
        (
            "pallas,totals=onehot",
            dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_TOTALS_IMPL="onehot"),
        ),
        # rounds_per_dispatch column: how many boosting rounds fuse into one
        # lax.scan dispatch (k=16 clamps to 10 on accelerator backends — the
        # known tunnel-wedge trigger; the child reports the effective K in
        # its rounds_per_dispatch field)
        (
            "pallas,k=1",
            dict(base, GRAFT_HIST_IMPL="pallas", BENCH_ROUNDS_PER_DISPATCH="1"),
        ),
        (
            "pallas,k=4",
            dict(base, GRAFT_HIST_IMPL="pallas", BENCH_ROUNDS_PER_DISPATCH="4"),
        ),
        (
            "pallas,k=16",
            dict(base, GRAFT_HIST_IMPL="pallas", BENCH_ROUNDS_PER_DISPATCH="16"),
        ),
    ]
    if n_devices > 1 and os.getenv("BENCH_MESH", "1") != "0":
        # only meaningful on a mesh: overlap pipelines the per-level
        # histogram COLLECTIVES (single-device rounds have none)
        configs.append(
            (
                "pallas,overlap=0",
                dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_HIST_OVERLAP="0"),
            )
        )
        # the comm column is only meaningful on a mesh (the child builds one
        # over all local devices — see main(); BENCH_MESH=0 disables the
        # mesh, which would silently resolve this probe back to psum and
        # burn a probe budget re-measuring the pallas baseline);
        # reduce_scatter is the A/B candidate against the psum baseline
        # pinned in every other entry
        configs.append(
            (
                "pallas,comm=reduce_scatter",
                dict(base, GRAFT_HIST_IMPL="pallas",
                     GRAFT_HIST_COMM="reduce_scatter"),
            )
        )
    mesh2d_shape = None
    if n_devices >= 4 and n_devices % 2 == 0 and os.getenv("BENCH_MESH", "1") != "0":
        # 2-D (data x feature) mesh column: the child reshapes its local
        # devices to BENCH_MESH_SHAPE (data x feature). Probed under both
        # comm lowerings — the 2-D reduce_scatter composition (scatter
        # along data, doubly-sharded scan, hierarchical winner merge) is
        # measurable here and composes into the winner like every other
        # knob (BENCH_MESH_SHAPE rides _CONFIG_KEYS into bench_winner.json)
        mesh2d_shape = "{}x2".format(n_devices // 2)
        configs.append(
            (
                "pallas,mesh2d",
                dict(base, GRAFT_HIST_IMPL="pallas",
                     BENCH_MESH_SHAPE=mesh2d_shape),
            )
        )
        configs.append(
            (
                "pallas,mesh2d,comm=reduce_scatter",
                dict(base, GRAFT_HIST_IMPL="pallas",
                     GRAFT_HIST_COMM="reduce_scatter",
                     BENCH_MESH_SHAPE=mesh2d_shape),
            )
        )
    note = "no probe succeeded"
    best_label, best_env, best_value = None, None, -1.0
    results = {}
    effective_k = {}  # label -> child-reported rounds_per_dispatch
    consecutive_timeouts = 0
    for label, env in configs:
        remaining = deadline - time.monotonic()
        if remaining < 10:
            note = "benchmark timed out after {}s".format(BENCH_TIMEOUT_S)
            break
        # cap so that even if probes hang, time remains for the final run
        # or the labeled CPU fallback
        per_probe_cap = max(90, (BENCH_TIMEOUT_S - 420) // max(len(configs), 1))
        budget = min(probe_timeout, per_probe_cap, max(10, int(remaining) - 60))
        child_env = dict(env)
        child_env["BENCH_ROUNDS_N"] = os.getenv("BENCH_PROBE_ROUNDS", "3")
        child_env["BENCH_WARMUP"] = "1"
        doc, err = _run_child(child_env, budget)
        if doc and doc.get("value", 0) > 0:
            consecutive_timeouts = 0
            sys.stderr.write("probe {}: {} r/s\n".format(label, doc["value"]))
            results[label] = doc["value"]
            if doc.get("rounds_per_dispatch") is not None:
                effective_k[label] = int(doc["rounds_per_dispatch"])
            if doc["value"] > best_value:
                best_label, best_env, best_value = label, dict(env), doc["value"]
                # incremental: kill-at-any-point leaves this parseable line
                _emit(
                    _result_doc(
                        best_value,
                        doc["metric"],
                        note="[probe best-so-far, hist_impl={}]".format(label),
                    )
                )
        else:
            sys.stderr.write("probe {} failed: {}\n".format(label, err))
            note = err or note
            if err and "timed out" in err:
                consecutive_timeouts += 1
                if consecutive_timeouts >= 2:
                    # circuit breaker: a wedged tunnel fails every probe
                    # identically — stop burning budget on it
                    note = "circuit breaker: 2 consecutive probe timeouts ({})".format(
                        err
                    )
                    sys.stderr.write(note + "\n")
                    break
    # the pallas probes vary INDEPENDENT knobs; compose every dimension
    # that clearly beat the pallas baseline into the final config (the
    # full run then measures — and honestly reports — the composition)
    if best_label and best_label.startswith("pallas") and "pallas" in results:
        base_v = results["pallas"]
        composed = dict(dict(configs)["pallas"])  # pallas baseline env
        parts = ["pallas"]
        for label, key, val in [
            ("pallas,vnodes=0", "GRAFT_HIST_VNODES", "0"),
            ("pallas,prec=bf16", "GRAFT_HIST_MM_PREC", "bf16"),
            ("pallas,route=onehot", "GRAFT_ROUTE_IMPL", "onehot"),
            ("pallas,comm=reduce_scatter", "GRAFT_HIST_COMM", "reduce_scatter"),
            ("pallas,overlap=0", "GRAFT_HIST_OVERLAP", "0"),
        ]:
            if results.get(label, 0.0) > base_v * 1.03:
                composed[key] = val
                parts.append(label.split(",", 1)[1])
        # totals is ONE knob with two candidate lowerings: compose the
        # better of the two when it beats the segment baseline
        totals_best = max(
            ("pallas,totals=onehot", "pallas,totals=pallas"),
            key=lambda l: results.get(l, 0.0),
        )
        if results.get(totals_best, 0.0) > base_v * 1.03:
            composed["GRAFT_TOTALS_IMPL"] = totals_best.rsplit("=", 1)[1]
            parts.append(totals_best.split(",", 1)[1])
        # mesh shape is ONE knob with the comm lowering measured jointly on
        # it: compose the better 2-D candidate when it beats the 1-D
        # baseline, carrying BOTH its keys (the 2-D winner's comm choice
        # overrides a 1-D comm compose — they were measured together)
        if mesh2d_shape is not None:
            mesh_best = max(
                ("pallas,mesh2d", "pallas,mesh2d,comm=reduce_scatter"),
                key=lambda l: results.get(l, 0.0),
            )
            # the override discards any composed 1-D comm choice, so it
            # must beat the measured candidate it invalidates, not just
            # the pallas baseline
            floor = base_v
            if composed.get("GRAFT_HIST_COMM", "psum") != "psum":
                floor = max(
                    floor, results.get("pallas,comm=reduce_scatter", 0.0)
                )
            if results.get(mesh_best, 0.0) > floor * 1.03:
                mesh_env = dict(configs)[mesh_best]
                composed["BENCH_MESH_SHAPE"] = mesh_env["BENCH_MESH_SHAPE"]
                composed["GRAFT_HIST_COMM"] = mesh_env["GRAFT_HIST_COMM"]
                # drop a 1-D comm part the override just invalidated — the
                # label must describe the config that actually runs
                parts = [p for p in parts if not p.startswith("comm=")]
                parts.append(mesh_best.split(",", 1)[1])
        # rounds_per_dispatch likewise: one knob, three candidate depths
        # (the baseline is pinned at the historical K=10). Candidates are
        # compared by the CHILD-REPORTED effective K: on accelerator
        # backends the k=16 child clamps to 10 (the tunnel-wedge guard),
        # making it the same config as the baseline — a >3% "win" there is
        # noise, and composing the requested 16 would record a config that
        # never ran
        base_k = effective_k.get("pallas")
        k_cands = [
            l for l in ("pallas,k=1", "pallas,k=4", "pallas,k=16")
            if effective_k.get(l) is not None and effective_k[l] != base_k
        ]
        if k_cands:
            k_best = max(k_cands, key=lambda l: results.get(l, 0.0))
            if results.get(k_best, 0.0) > base_v * 1.03:
                composed["BENCH_ROUNDS_PER_DISPATCH"] = str(effective_k[k_best])
                parts.append("k={}".format(effective_k[k_best]))
        if len(parts) > 1:
            best_label, best_env = "+".join(parts), composed
    return best_label, best_env, best_value, results, dict(configs), note


def _measure_config(label, env, deadline, reserve, suffix, save_ok):
    """Run the full measurement for one config under the tail-reserving
    budget policy; a composed config (never probed as a unit, so a bad
    interaction -> bigger compile -> wedge is possible) gets a tighter
    clamp. Emits the result line and persists the winner on success.
    -> (done, err)."""
    remaining = deadline - time.monotonic()
    if remaining < 30:
        return False, "no budget left for a full run"
    budget = max(30, int(remaining) - reserve)
    if "+" in (label or ""):
        budget = min(budget, int(remaining * 0.6))
    doc, err = _run_child(env, budget)
    if doc:
        doc["metric"] = "{} [hist_impl={}{}]".format(doc["metric"], label, suffix)
        _emit(doc)
        if save_ok:
            _save_winner(label, env, doc.get("value", 0.0), "full run")
        return True, None
    return False, err


def _supervised_main():
    """Supervision tree: pre-check backend -> (pinned config | persisted
    winner | probe matrix) -> full measurement -> labeled CPU fallback.
    Every child runs under a hard timeout; a wedging impl or a dead TPU
    tunnel cannot take the bench down."""
    deadline = time.monotonic() + BENCH_TIMEOUT_S

    want_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
    n_devices = 1
    if not want_cpu:
        global _backend_init_error
        precheck_budget = int(os.getenv("BENCH_PRECHECK_TIMEOUT_S", "90"))
        healthy, n_devices, backend_err = _backend_healthy(precheck_budget)
        if not healthy:
            _backend_init_error = backend_err
            sys.stderr.write(
                "backend pre-check failed within {}s: {}\n".format(
                    precheck_budget, (backend_err or {}).get("error", "?")
                )
            )
            _cpu_fallback(deadline, "backend pre-check failed/hung")
            return

    results = {}
    # only probe-matrix / persisted-winner runs may update bench_winner.json;
    # a pinned-impl or explicit-CPU run must not clobber the TPU winner
    save_ok = False
    if os.environ.get("GRAFT_HIST_IMPL"):
        best_label = os.environ["GRAFT_HIST_IMPL"]
        best_env, best_value = {}, -1.0
        note = "pinned config produced no result"
    elif want_cpu:
        # explicit CPU run: the TPU probe matrix / persisted TPU winner are
        # meaningless here — flat scatter is the measured CPU winner
        best_label, best_env, best_value = "flat", {"GRAFT_HIST_IMPL": "flat"}, -1.0
        note = "cpu run produced no result"
    else:
        save_ok = True
        winner_label, winner_env = (None, None)
        if os.environ.get("BENCH_REPROBE") != "1":
            winner_label, winner_env, winner_stale = _load_winner()
            if winner_env and winner_stale:
                sys.stderr.write(
                    "persisted winner predates the current code revision; "
                    "re-probing the full matrix\n"
                )
                winner_label, winner_env = None, None
        if winner_env:
            sys.stderr.write(
                "using persisted winner {} ({}); BENCH_REPROBE=1 to re-probe\n".format(
                    winner_label, WINNER_FILE
                )
            )
            best_label, best_env, best_value = winner_label, winner_env, -1.0
            note = "persisted winner produced no result"
        else:
            (
                best_label,
                best_env,
                best_value,
                results,
                config_map,
                note,
            ) = _probe_matrix(deadline, n_devices)

    remaining = deadline - time.monotonic()
    if best_label is not None and remaining >= 10:
        # reserve tail time so a hung full run still leaves room for the
        # CPU fallback (primary run reserves more than the salvage runs)
        done, err = _measure_config(best_label, best_env, deadline, 240, "", save_ok)
        if done:
            return
        note = err or note
        if save_ok and not results:
            # ADVICE r3: the persisted winner's full run failed (e.g. a
            # toolchain change wedges its config) — re-probe the matrix
            # with the remaining budget instead of dumping straight to the
            # CPU fallback
            if deadline - time.monotonic() >= 180:
                sys.stderr.write(
                    "persisted winner failed ({}); re-probing\n".format(
                        (note or "")[:120]
                    )
                )
                (
                    best_label,
                    best_env,
                    best_value,
                    results,
                    config_map,
                    note,
                ) = _probe_matrix(deadline, n_devices)
                if best_label is not None and best_value > 0:
                    done, err = _measure_config(
                        best_label, best_env, deadline, 120,
                        " after persisted winner failed", save_ok,
                    )
                    if done:
                        return
                    note = err or note
        if "+" in (best_label or "") and results:
            # fall back to the best INDIVIDUALLY-probed config, taken from
            # the probe matrix itself (single source of the label->env map)
            fallback_label = max(results, key=results.get)
            fb_env = dict(config_map.get(fallback_label, {}))
            if fb_env:
                done, err = _measure_config(
                    fallback_label, fb_env, deadline, 120,
                    " after composed config failed", save_ok,
                )
                if done:
                    return
                note = err or note
        if best_value > 0:
            # full run died but the probes measured something real: report
            # the best probe instead of a 0.0 (clearly labeled)
            _emit(
                _result_doc(
                    best_value,
                    "boosting rounds/sec (synthetic, probe-only: "
                    "full run failed: {}) [hist_impl={}]".format(
                        note[:120], best_label
                    ),
                )
            )
            if save_ok:
                _save_winner(best_label, best_env, best_value, "probe")
            return
    elif best_label is not None:
        note = "benchmark timed out after {}s".format(BENCH_TIMEOUT_S)
    _cpu_fallback(deadline, "TPU measurement failed: " + note)


N_ROWS = int(os.getenv("BENCH_ROWS", "1000000"))
N_FEATURES = int(os.getenv("BENCH_FEATURES", "28"))
MAX_DEPTH = int(os.getenv("BENCH_MAX_DEPTH", "8"))
WARMUP_ROUNDS = int(os.getenv("BENCH_WARMUP", "3"))
BENCH_ROUNDS = int(os.getenv("BENCH_ROUNDS_N", "20"))
NORTH_STAR_ROUNDS_PER_SEC = 5.0


def _make_data(n, d, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    logit = X[:, 0] * 0.8 + X[:, 1] * X[:, 2] * 0.5 + np.sin(X[:, 3]) - 0.2
    y = (logit + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return X, y


def _task_setup(n, d, seed=0):
    """BENCH_TASK selects the measured workload: ``binary`` (default; BASELINE
    config #2 Higgs-like), ``multiclass`` (#3 CoverType-like, 7 classes),
    ``ranking`` (#4 MSLR-like LambdaMART, ~100-doc groups), or ``lossguide``
    (LightGBM-style leaf-wise growth at BENCH_MAX_LEAVES, default 255 — the
    O(max_leaves * n * d) rescan cost question, VERDICT r3 #7). Returns
    (DataMatrix kwargs-ready pieces, params dict, task label)."""
    task = os.getenv("BENCH_TASK", "binary")
    rng = np.random.RandomState(seed)
    X, y = _make_data(n, d, seed)
    groups = None
    if task == "binary":
        params = {"objective": "binary:logistic"}
    elif task == "lossguide":
        params = {
            "objective": "binary:logistic",
            "grow_policy": "lossguide",
            "max_leaves": int(os.getenv("BENCH_MAX_LEAVES", "255")),
            "max_depth": 0,
        }
    elif task == "multiclass":
        score = X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n)
        y = np.digitize(score, np.quantile(score, np.linspace(0, 1, 8)[1:-1]))
        y = y.astype(np.float32)
        params = {"objective": "multi:softmax", "num_class": 7}
    elif task == "ranking":
        rel = X[:, 0] + np.sin(X[:, 1]) + 0.5 * rng.randn(n)
        y = np.digitize(rel, np.quantile(rel, [0.5, 0.75, 0.9, 0.97])).astype(
            np.float32
        )
        group_size = 100
        groups = np.full(n // group_size, group_size, np.int64)
        n_used = int(groups.sum())
        X, y = X[:n_used], y[:n_used]
        params = {"objective": "rank:ndcg"}
    else:
        raise ValueError("BENCH_TASK must be binary|multiclass|ranking|lossguide")
    return X, y, groups, params, task


def _final_train_metric(margins, y, task):
    """(metric name, value) of the trained margins on the bench's own train
    set — the model-quality stamp next to rounds/sec. Host numpy on the
    final margins only (one gather after the measured window, never inside
    it). Ranking would need grouped NDCG; skipped."""
    m = np.asarray(margins, np.float64)
    rows = len(y)
    eps = 1e-7
    if task in ("binary", "lossguide"):
        p = np.clip(1.0 / (1.0 + np.exp(-m.reshape(-1)[:rows])), eps, 1 - eps)
        return "logloss", float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
    if task == "multiclass":
        m = m[:rows]
        e = np.exp(m - m.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        picked = p[np.arange(rows), y.astype(np.int64)]
        return "mlogloss", float(-np.mean(np.log(np.clip(picked, eps, None))))
    return None, None


def main():
    # detect a dead accelerator backend up front; an honest, clearly-labeled
    # CPU number is more useful than a 0.0 placeholder
    backend_note = ""
    backend_err = None
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # explicit CPU request: don't let the site plugin's "axon,cpu" win
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        jax.devices()
    else:
        t0 = time.monotonic()
        try:
            jax.devices()
        except RuntimeError as e:
            sys.stderr.write("TPU backend unavailable: {}\n".format(e))
            backend_err = {
                "error": str(e)[:400],
                "elapsed_s": round(time.monotonic() - t0, 1),
            }
            jax.config.update("jax_platforms", "cpu")
            jax.devices()
            backend_note = " [CPU FALLBACK - TPU backend unavailable]"

    # persistent XLA compile cache (GRAFT_COMPILE_CACHE_DIR): armed before
    # the first compile so repeat bench children and short probes stop
    # paying first-round compile (the session arms it too; this covers the
    # warmup path and keeps the arming ahead of any jit below)
    from sagemaker_xgboost_container_tpu.utils.compile_cache import (
        maybe_enable_compile_cache,
    )

    maybe_enable_compile_cache()

    # attribution plumbing: the jax.monitoring compile listener feeds
    # compile_stats, and SM_TRACE_DEVICE_SYNC=1 makes the session fence
    # every dispatch so host_dispatch/device_sync phases are measured (the
    # bench loop blocks per dispatch anyway, so the fence costs nothing)
    os.environ.setdefault("SM_TRACE_DEVICE_SYNC", "1")
    # arm the device window too: the session's compiled-cost introspection
    # (training.compiled) plus the roofline stamp below ride the same gate
    os.environ.setdefault("SM_DEVICE_TELEMETRY", "1")
    # and the model window: the final JSON stamps a train metric + the last
    # round's learning stats so BENCH_* snapshots track model quality next
    # to rounds/sec (a perf win that degrades quality must be visible)
    os.environ.setdefault("SM_MODEL_TELEMETRY", "1")
    from sagemaker_xgboost_container_tpu.telemetry import register_runtime_gauges
    from sagemaker_xgboost_container_tpu.telemetry.cluster import compile_stats

    register_runtime_gauges()

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models.booster import (
        TrainConfig,
        _TrainingSession,
    )
    from sagemaker_xgboost_container_tpu.models.forest import Forest

    X, y, groups, task_params, task = _task_setup(N_ROWS, N_FEATURES)
    dtrain = DataMatrix(X, labels=y, groups=groups)
    rounds_per_dispatch = int(os.getenv("BENCH_ROUNDS_PER_DISPATCH", "10"))
    if task == "lossguide":
        # a K-round scan body contains K * (max_leaves - 1) unrolled split
        # steps; at 255 leaves even K=10 is a wedge-scale compile on the
        # tunneled chip — keep the program one tree deep
        rounds_per_dispatch = min(rounds_per_dispatch, 1)
    if jax.default_backend() != "cpu" and rounds_per_dispatch > 10:
        # wedge playbook (docs/ROUND2_STATE.md): compiling a >10-iteration
        # scan has twice wedged the tunneled chip for hours — clamp
        sys.stderr.write(
            "BENCH_ROUNDS_PER_DISPATCH={} clamped to 10 on the {} backend "
            "(K>10 compiles are a known tunnel-wedge trigger)\n".format(
                rounds_per_dispatch, jax.default_backend()
            )
        )
        rounds_per_dispatch = 10
    params = dict(task_params)
    # task params may pin their own depth policy (lossguide: max_depth=0)
    params.setdefault("max_depth", MAX_DEPTH)
    params.update(
        eta=0.2,
        tree_method="hist",
        max_bin=256,
        _rounds_per_dispatch=rounds_per_dispatch,
    )
    config = TrainConfig(params)
    forest = Forest(
        objective_name=config.objective,
        objective_params={"num_class": config.num_class}
        if config.num_class
        else None,
        base_score=config.base_score,
        num_feature=dtrain.num_col,
        num_class=config.num_class,
    )
    # multi-device hosts measure the full data-parallel round (rows sharded
    # over all local devices, GRAFT_HIST_COMM selecting the histogram
    # collective) — the north-star is a v5p MESH rate, not a single chip.
    # BENCH_MESH=0 opts out; single-device runs (incl. the CPU fallback,
    # which never sets xla_force_host_platform_device_count) are unchanged.
    mesh = None
    mesh_note = ""
    if os.getenv("BENCH_MESH", "1") != "0" and len(jax.devices()) > 1:
        from jax.sharding import Mesh

        # BENCH_MESH_SHAPE=RxC builds a 2-D (data x feature) mesh over the
        # first R*C local devices — the communication-optimal 2-D lowering
        # (GRAFT_HIST_COMM=reduce_scatter x feature axis) is measured on
        # exactly the topology it targets. Empty/unset: the auto 1-D mesh.
        shape_spec = os.getenv("BENCH_MESH_SHAPE", "").strip()
        if shape_spec:
            try:
                rows, cols = (int(v) for v in shape_spec.lower().split("x"))
                if rows < 1 or cols < 1 or rows * cols > len(jax.devices()):
                    raise ValueError("shape exceeds device count")
                mesh = Mesh(
                    np.array(jax.devices()[: rows * cols]).reshape(rows, cols),
                    axis_names=("data", "feature"),
                )
                mesh_note = ", mesh={}x{} (data x feature) comm={}".format(
                    rows, cols, os.getenv("GRAFT_HIST_COMM", "psum")
                )
            except (ValueError, TypeError) as e:
                sys.stderr.write(
                    "BENCH_MESH_SHAPE={!r} invalid ({}); falling back to the "
                    "1-D data mesh\n".format(shape_spec, e)
                )
                mesh = None
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), axis_names=("data",))
            mesh_note = ", mesh={}xdata comm={}".format(
                len(jax.devices()), os.getenv("GRAFT_HIST_COMM", "psum")
            )
    session = _TrainingSession(config, dtrain, [], forest, mesh=mesh)

    # the round-latency distribution rides the same telemetry registry the
    # trainer uses (training_round_seconds / training_phase_seconds), so the
    # bench line carries registry-derived p50/p95 + a phase breakdown, not
    # just the mean — BENCH_*.json trajectory entries get a real shape
    from sagemaker_xgboost_container_tpu.telemetry import REGISTRY, span
    from sagemaker_xgboost_container_tpu.training.profiling import ROUND_HISTOGRAM

    round_hist = REGISTRY.histogram(ROUND_HISTOGRAM, help="Boosting round wall time")

    def _phase_sums():
        sums = {}
        for name, kind, _help, series in REGISTRY.collect():
            if name == "training_phase_seconds" and kind == "histogram":
                for metric in series:
                    sums[metric.labels.get("phase", "unknown")] = metric.sum
        return sums

    with span("warmup"):
        done = 0
        while done < WARMUP_ROUNDS:
            done += len(session.run_rounds()[0])
        jax.block_until_ready(session.margins)

    warmup_compile_s = compile_stats()["seconds"]
    pre_phases = _phase_sums()
    start = time.perf_counter()
    done = 0
    with span("measure"):
        # block per dispatch (not once at the end) so per-round latency is
        # observable; with K rounds per dispatch the extra syncs are ~2 of
        # BENCH_ROUNDS/K and amortize to noise
        while done < BENCH_ROUNDS:
            t0 = time.perf_counter()
            n = len(session.run_rounds()[0])
            jax.block_until_ready(session.margins)
            dt = time.perf_counter() - t0
            for _ in range(n):
                round_hist.observe(dt / max(n, 1))
            done += n
    elapsed = time.perf_counter() - start

    post_phases = _phase_sums()
    phases_ms = {k: round(v * 1000, 3) for k, v in post_phases.items()}

    # attribution of the MEASURED window: compile (jax.monitoring listener
    # delta; warmup compile reported separately — that's where first-round
    # compile lives), host dispatch vs device compute (the per-dispatch
    # fence spans), and the calibrated collective share on a mesh
    def _delta(key):
        return max(post_phases.get(key, 0.0) - pre_phases.get(key, 0.0), 0.0)

    from sagemaker_xgboost_container_tpu.telemetry import get_round_fields
    from sagemaker_xgboost_container_tpu.training.profiling import (
        attribution_fields,
    )

    compile_ms = max(compile_stats()["seconds"] - warmup_compile_s, 0.0) * 1000
    # a compile that fired inside a fenced dispatch is already inside the
    # host_dispatch span — re-attribute like RoundTimer does
    host_ms = max(_delta("host_dispatch") * 1000 - compile_ms, 0.0)
    attribution = attribution_fields(
        total_ms=elapsed * 1000.0,
        compile_ms=compile_ms,
        host_ms=host_ms,
        device_ms=_delta("device_sync") * 1000,
        collective_ms=float(get_round_fields().get("hist_comm_ms") or 0.0)
        * done,
    )
    attribution["warmup_compile_ms"] = round(warmup_compile_s * 1000, 3)

    rounds_per_sec = done / elapsed
    shape_note = (
        "{} leaves (leaf-wise)".format(params["max_leaves"])
        if task == "lossguide"
        else "depth {}".format(MAX_DEPTH)
    )
    doc = {
        "metric": "boosting rounds/sec (synthetic, {} rows x {} feat, {}, {}{}){}".format(
            N_ROWS, N_FEATURES, shape_note, params["objective"],
            mesh_note, backend_note
        ),
        "value": round(rounds_per_sec, 3),
        "unit": "rounds/sec",
        "vs_baseline": round(rounds_per_sec / NORTH_STAR_ROUNDS_PER_SEC, 3),
        "p50_ms": round(round_hist.quantile(0.5) * 1000, 3),
        "p95_ms": round(round_hist.quantile(0.95) * 1000, 3),
        "rounds_per_dispatch": session.rounds_per_dispatch,
        "phases_ms": phases_ms,
        "attribution": attribution,
    }
    # roofline stamp for the measured window: achieved FLOPs/s and bytes/s
    # against the compiled cost captured at session build (device window)
    from sagemaker_xgboost_container_tpu.telemetry import device as device_telemetry

    device_ms = _delta("device_sync") * 1000
    source = "device_sync"
    if device_ms <= 0.0:
        device_ms = max(elapsed * 1000.0 - compile_ms - host_ms, 0.0)
        source = "residual"
    roofline = device_telemetry.maybe_roofline(device_ms, done, source)
    if roofline is not None:
        doc["roofline"] = roofline
    # model-quality stamp (SM_MODEL_TELEMETRY): the final train metric plus
    # the last dispatch's on-device learning stats — BENCH_* snapshots carry
    # quality next to throughput
    try:
        metric_name, metric_value = _final_train_metric(session.margins, y, task)
        model_doc = {}
        if metric_name is not None:
            model_doc["train_metric"] = metric_name
            model_doc["train_value"] = round(metric_value, 6)
        if session.last_learning_stats:
            model_doc["learning"] = {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in session.last_learning_stats[-1].items()
            }
        if model_doc:
            doc["model"] = model_doc
    except Exception as e:
        sys.stderr.write("model-quality stamp failed: {}\n".format(e))
    if backend_err is not None:
        doc["backend_init_error"] = backend_err
    print(json.dumps(doc))


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        _supervised_main()
