#!/usr/bin/env python
"""Benchmark: boosting rounds/sec of the XLA histogram tree builder.

Measures steady-state boosting throughput on a synthetic Higgs-like binary
classification task (BASELINE.md config #2: dense numeric features,
binary:logistic, hist). Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "rounds/sec", "vs_baseline": N}

vs_baseline is measured against the north-star target of 5 boosting
rounds/sec (BASELINE.json) — the reference publishes no numbers of its own
(BASELINE.md: published = {}).
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

# Self-supervision: the TPU tunnel in this environment can wedge indefinitely
# (see memory: tpu-tunnel-quirks); the parent process runs the real benchmark
# as a child under a hard timeout so ONE JSON line is always printed.
BENCH_TIMEOUT_S = int(os.getenv("BENCH_TIMEOUT_S", "2400"))


def _run_child(env_extra, timeout):
    """One supervised child run -> parsed JSON dict or (None, note)."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env.update(env_extra)
    try:
        result = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        for line in reversed(result.stdout.splitlines()):
            if line.startswith("{"):
                return json.loads(line), None
        err_tail = " | ".join(result.stderr.strip().splitlines()[-3:])[-400:]
        return None, "child produced no result (rc={}): {}".format(
            result.returncode, err_tail
        )
    except subprocess.TimeoutExpired:
        return None, "child timed out after {}s".format(timeout)


def _supervised_main():
    """A/B the histogram impls (each in its own supervised child — a
    wedging impl or a dead TPU tunnel cannot take the bench down), then run
    the full measurement with the winner. GRAFT_HIST_IMPL pins one impl."""
    deadline = time.monotonic() + BENCH_TIMEOUT_S
    probe_timeout = int(os.getenv("BENCH_PROBE_TIMEOUT_S", "600"))
    if os.environ.get("GRAFT_HIST_IMPL"):
        configs = [(os.environ["GRAFT_HIST_IMPL"], {})]
    else:
        # impl x operand-precision x lowering matrix (bf16 operands are
        # quality-validated: matches f32 val-logloss/auc on the bench task,
        # BASELINE.md). Every knob pinned in every entry: an inherited env
        # would otherwise silently collapse the A/B. vnodes=0 probes guard
        # against the virtual-node packing regressing on real hardware.
        base = {
            "GRAFT_HIST_MM_PREC": "bf16x2",
            "GRAFT_HIST_VNODES": "1",
            "GRAFT_ROUTE_IMPL": "gather",
            "GRAFT_TOTALS_IMPL": "segment",
        }
        configs = [
            ("flat", dict(base, GRAFT_HIST_IMPL="flat")),
            ("matmul", dict(base, GRAFT_HIST_IMPL="matmul")),
            ("pallas", dict(base, GRAFT_HIST_IMPL="pallas")),
            (
                "pallas,vnodes=0",
                dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_HIST_VNODES="0"),
            ),
            (
                "pallas,prec=bf16",
                dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_HIST_MM_PREC="bf16"),
            ),
            (
                "pallas,route=onehot",
                dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_ROUTE_IMPL="onehot"),
            ),
            (
                "pallas,totals=pallas",
                dict(base, GRAFT_HIST_IMPL="pallas", GRAFT_TOTALS_IMPL="pallas"),
            ),
        ]
    note = "no probe succeeded"
    best_label, best_env, best_value = None, None, -1.0
    results = {}
    if len(configs) == 1:
        best_label, best_env = configs[0][0], dict(configs[0][1])
    else:
        for label, env in configs:
            remaining = deadline - time.monotonic()
            if remaining < 10:
                note = "benchmark timed out after {}s".format(BENCH_TIMEOUT_S)
                break
            # cap so that even if EVERY probe hangs (wedged tunnel), ~600s
            # remain for the final run / the labeled CPU fallback
            per_probe_cap = max(60, (BENCH_TIMEOUT_S - 600) // max(len(configs), 1))
            budget = min(probe_timeout, per_probe_cap, max(10, int(remaining) - 60))
            child_env = dict(env)
            child_env["BENCH_ROUNDS_N"] = os.getenv("BENCH_PROBE_ROUNDS", "3")
            child_env["BENCH_WARMUP"] = "1"
            doc, err = _run_child(child_env, budget)
            if doc and doc.get("value", 0) > 0:
                sys.stderr.write("probe {}: {} r/s\n".format(label, doc["value"]))
                results[label] = doc["value"]
                if doc["value"] > best_value:
                    best_label, best_env, best_value = label, dict(env), doc["value"]
            else:
                sys.stderr.write("probe {} failed: {}\n".format(label, err))
                note = err or note
        # the pallas probes vary INDEPENDENT knobs; compose every dimension
        # that clearly beat the pallas baseline into the final config (the
        # full run then measures — and honestly reports — the composition)
        if best_label and best_label.startswith("pallas") and "pallas" in results:
            base_v = results["pallas"]
            composed = dict(dict(configs)["pallas"])  # pallas baseline env
            parts = ["pallas"]
            for label, key, val in [
                ("pallas,vnodes=0", "GRAFT_HIST_VNODES", "0"),
                ("pallas,prec=bf16", "GRAFT_HIST_MM_PREC", "bf16"),
                ("pallas,route=onehot", "GRAFT_ROUTE_IMPL", "onehot"),
                ("pallas,totals=pallas", "GRAFT_TOTALS_IMPL", "pallas"),
            ]:
                if results.get(label, 0.0) > base_v * 1.03:
                    composed[key] = val
                    parts.append(label.split(",", 1)[1])
            if len(parts) > 1:
                best_label, best_env = "+".join(parts), composed
    remaining = deadline - time.monotonic()
    if best_label is not None and remaining >= 10:
        # the composed config was never probed as a unit: cap its run so a
        # bad interaction (bigger compile -> wedge) leaves time to retry
        # with the best individually-measured config
        composed_run = "+" in (best_label or "")
        budget = int(remaining if not composed_run else max(60, remaining * 0.6))
        doc, err = _run_child(best_env, budget)
        if doc:
            doc["metric"] = "{} [hist_impl={}]".format(doc["metric"], best_label)
            print(json.dumps(doc))
            return
        note = err or "benchmark timed out after {}s".format(BENCH_TIMEOUT_S)
        if composed_run and results:
            fallback_label = max(results, key=results.get)
            fb_env = next(
                (dict(env) for lbl, env in configs if lbl == fallback_label), {}
            )
            remaining = deadline - time.monotonic()
            if remaining >= 30:
                doc, err = _run_child(fb_env, int(remaining))
                if doc:
                    doc["metric"] = "{} [hist_impl={} after composed config failed]".format(
                        doc["metric"], fallback_label
                    )
                    print(json.dumps(doc))
                    return
        if best_value > 0:
            # full run died but the probes measured something real: report
            # the best probe instead of a 0.0 (clearly labeled)
            print(
                json.dumps(
                    {
                        "metric": "boosting rounds/sec (synthetic, probe-only: "
                        "full run failed: {}) [hist_impl={}]".format(
                            note[:120], best_label
                        ),
                        "value": round(best_value, 3),
                        "unit": "rounds/sec",
                        "vs_baseline": round(
                            best_value / NORTH_STAR_ROUNDS_PER_SEC, 3
                        ),
                    }
                )
            )
            return
    elif best_label is not None:
        note = "benchmark timed out after {}s".format(BENCH_TIMEOUT_S)
    remaining = deadline - time.monotonic()
    if best_label is None and remaining >= 60:
        # every TPU probe hung/failed (wedged tunnel): an honest, labeled
        # CPU number beats a 0.0 (same policy as the r1 init-failure path,
        # extended to mid-run wedges where init HANGS instead of raising)
        doc, err = _run_child(
            {"JAX_PLATFORMS": "cpu", "GRAFT_HIST_IMPL": "flat"},
            int(min(remaining, 900)),
        )
        if doc:
            doc["metric"] = (
                "{} [CPU FALLBACK - all TPU probes failed: {}]".format(
                    doc["metric"], note[:160]
                )
            )
            print(json.dumps(doc))
            return
    print(
        json.dumps(
            {
                "metric": "boosting rounds/sec (synthetic Higgs-like) — FAILED: " + note,
                "value": 0.0,
                "unit": "rounds/sec",
                "vs_baseline": 0.0,
            }
        )
    )

N_ROWS = int(os.getenv("BENCH_ROWS", "1000000"))
N_FEATURES = int(os.getenv("BENCH_FEATURES", "28"))
MAX_DEPTH = int(os.getenv("BENCH_MAX_DEPTH", "8"))
WARMUP_ROUNDS = int(os.getenv("BENCH_WARMUP", "3"))
BENCH_ROUNDS = int(os.getenv("BENCH_ROUNDS_N", "20"))
NORTH_STAR_ROUNDS_PER_SEC = 5.0


def _make_data(n, d, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    logit = X[:, 0] * 0.8 + X[:, 1] * X[:, 2] * 0.5 + np.sin(X[:, 3]) - 0.2
    y = (logit + rng.randn(n) * 0.5 > 0).astype(np.float32)
    return X, y


def _task_setup(n, d, seed=0):
    """BENCH_TASK selects the measured workload: ``binary`` (default; BASELINE
    config #2 Higgs-like), ``multiclass`` (#3 CoverType-like, 7 classes), or
    ``ranking`` (#4 MSLR-like LambdaMART, ~100-doc groups). Returns
    (DataMatrix kwargs-ready pieces, params dict, task label)."""
    task = os.getenv("BENCH_TASK", "binary")
    rng = np.random.RandomState(seed)
    X, y = _make_data(n, d, seed)
    groups = None
    if task == "binary":
        params = {"objective": "binary:logistic"}
    elif task == "multiclass":
        score = X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.3 * rng.randn(n)
        y = np.digitize(score, np.quantile(score, np.linspace(0, 1, 8)[1:-1]))
        y = y.astype(np.float32)
        params = {"objective": "multi:softmax", "num_class": 7}
    elif task == "ranking":
        rel = X[:, 0] + np.sin(X[:, 1]) + 0.5 * rng.randn(n)
        y = np.digitize(rel, np.quantile(rel, [0.5, 0.75, 0.9, 0.97])).astype(
            np.float32
        )
        group_size = 100
        groups = np.full(n // group_size, group_size, np.int64)
        n_used = int(groups.sum())
        X, y = X[:n_used], y[:n_used]
        params = {"objective": "rank:ndcg"}
    else:
        raise ValueError("BENCH_TASK must be binary|multiclass|ranking")
    return X, y, groups, params, task


def main():
    # detect a dead accelerator backend up front; an honest, clearly-labeled
    # CPU number is more useful than a 0.0 placeholder
    backend_note = ""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # explicit CPU request: don't let the site plugin's "axon,cpu" win
        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
        jax.devices()
    else:
        try:
            jax.devices()
        except RuntimeError as e:
            sys.stderr.write("TPU backend unavailable: {}\n".format(e))
            jax.config.update("jax_platforms", "cpu")
            jax.devices()
            backend_note = " [CPU FALLBACK - TPU backend unavailable]"

    from sagemaker_xgboost_container_tpu.data.matrix import DataMatrix
    from sagemaker_xgboost_container_tpu.models.booster import (
        TrainConfig,
        _TrainingSession,
    )
    from sagemaker_xgboost_container_tpu.models.forest import Forest

    X, y, groups, task_params, task = _task_setup(N_ROWS, N_FEATURES)
    dtrain = DataMatrix(X, labels=y, groups=groups)
    params = dict(
        task_params,
        max_depth=MAX_DEPTH,
        eta=0.2,
        tree_method="hist",
        max_bin=256,
        _rounds_per_dispatch=int(os.getenv("BENCH_ROUNDS_PER_DISPATCH", "10")),
    )
    config = TrainConfig(params)
    forest = Forest(
        objective_name=config.objective,
        objective_params={"num_class": config.num_class}
        if config.num_class
        else None,
        base_score=config.base_score,
        num_feature=dtrain.num_col,
        num_class=config.num_class,
    )
    session = _TrainingSession(config, dtrain, [], forest)

    import jax

    done = 0
    while done < WARMUP_ROUNDS:
        done += len(session.run_rounds()[0])
    jax.block_until_ready(session.margins)

    start = time.perf_counter()
    done = 0
    while done < BENCH_ROUNDS:
        done += len(session.run_rounds()[0])
    jax.block_until_ready(session.margins)
    elapsed = time.perf_counter() - start

    rounds_per_sec = done / elapsed
    print(
        json.dumps(
            {
                "metric": "boosting rounds/sec (synthetic, {} rows x {} feat, depth {}, {}){}".format(
                    N_ROWS, N_FEATURES, MAX_DEPTH, params["objective"], backend_note
                ),
                "value": round(rounds_per_sec, 3),
                "unit": "rounds/sec",
                "vs_baseline": round(rounds_per_sec / NORTH_STAR_ROUNDS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        _supervised_main()
